"""SLO-aware adaptive execution tests (ISSUE 14, marker ``serve``).

Covers the ISSUE-14 acceptance surface: the margin -> probe-rung
policy units, difficulty-margin separation on clustered data, the
recall-band + probed-work acceptance (adaptive rungs within 0.01 of
exhaustive recall at a >= 4x mean probed-list reduction on the
easy-dominated mix), trace stability over the full (bucket, k, rung)
ladder, the exhaustive escape hatch served bitwise vs the non-adaptive
path (tombstones + user prefilters composed), deadline-driven serving
(priority-lane linger skip, shed under an injected
``slow@stage:serve.dispatch`` stall), per-index admission quotas, and
the swap-re-derives-the-ladder regression."""

import dataclasses
import time

import numpy as np
import pytest

from raft_tpu import obs, serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.resilience import faultinject
from raft_tpu.serve.adaptive import (
    AdaptivePolicy,
    probe_ladder,
    service_estimate_ms,
)

pytestmark = [pytest.mark.serve, pytest.mark.threadsan]

DIM = 16
N_LISTS = 8


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    faultinject.clear()
    yield
    faultinject.clear()
    tuning.reload()


@pytest.fixture(scope="module")
def clustered():
    """Tight clusters + easy (perturbed-row) and hard (cluster-midpoint)
    query pools — the regime where the coarse margin is informative."""
    rng = np.random.default_rng(21)
    centers = rng.uniform(-5, 5, (N_LISTS, DIM)).astype(np.float32)
    x = (centers[rng.integers(0, N_LISTS, 512)]
         + 0.05 * rng.standard_normal((512, DIM))).astype(np.float32)
    easy = (x[rng.integers(0, 512, 24)]
            + 0.02 * rng.standard_normal((24, DIM))).astype(np.float32)
    a, b = (rng.integers(0, N_LISTS, 8) for _ in range(2))
    hard = ((centers[a] + centers[b]) / 2
            + 0.1 * rng.standard_normal((8, DIM))).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=N_LISTS, kmeans_n_iters=6), x)
    return x, easy, hard, index


def _params(**kw):
    kw.setdefault("max_batch_rows", 4)
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("max_k", 4)
    kw.setdefault("adaptive_probes", True)
    return serve.ServeParams(**kw)


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------


def test_probe_ladder_shape():
    assert probe_ladder(16) == (1, 2, 4, 8, 16)
    assert probe_ladder(10) == (1, 2, 4, 8, 10)   # non-pow2 ceiling rides
    assert probe_ladder(1) == (1,)


def test_policy_margin_mapping():
    pol = AdaptivePolicy(ladder=probe_ladder(16), list_cap=128,
                         easy_margin=0.2, floor_margin=0.02)
    assert pol.choose_idx(0.5) == 0                   # easy: min rung
    assert pol.choose_idx(0.2) == 0
    # the escape hatch: ambiguous margins serve the exhaustive TOP rung
    assert pol.rung(pol.choose_idx(0.001)) == 16
    assert pol.rung(pol.choose_idx(float("nan"))) == 16
    # interpolation is monotone: harder -> deeper
    idxs = [pol.choose_idx(m) for m in (0.18, 0.12, 0.06, 0.03)]
    assert idxs == sorted(idxs)
    assert all(0 < i < len(pol.ladder) for i in idxs)


def test_policy_k_floor_and_refine_rungs():
    pol = AdaptivePolicy(ladder=probe_ladder(16), list_cap=8,
                         easy_margin=0.2, floor_margin=0.02,
                         refine_ratio=4)
    # a rung must keep rung * cap >= k: k=20 with cap=8 needs >= 4 probes
    assert pol.rung(pol.choose_idx(0.9, k=20)) == 4
    assert pol.min_idx(1) == 0
    # per-rung rabitq refine: the easiest rung halves the over-fetch,
    # everything else (incl. the escape hatch) keeps the default —
    # bitwise vs the non-adaptive pipeline
    assert pol.refine_for(0) == 2
    assert pol.refine_for(len(pol.ladder) - 1) == 4
    assert pol.refine_ladder() == (2, 4)
    assert AdaptivePolicy(ladder=(1, 2), list_cap=8, easy_margin=0.2,
                          floor_margin=0.02).refine_for(0) == 1


def test_service_estimate_reads_captured_table():
    # the committed cpu.json carries serve_service (bucket, rung)
    # medians (captured 2026-08-04) — the batcher's slack test reads
    # THESE, not a hardcoded guess
    est = service_estimate_ms(8, 1)
    assert est is not None and est > 0


# ---------------------------------------------------------------------------
# margins
# ---------------------------------------------------------------------------


def test_margins_separate_easy_from_hard(clustered):
    x, easy, hard, index = clustered
    m_easy = np.asarray(ivf_flat.coarse_margins(index, easy))
    m_hard = np.asarray(ivf_flat.coarse_margins(index, hard))
    assert ((0 <= m_easy) & (m_easy <= 1)).all()
    assert ((0 <= m_hard) & (m_hard <= 1)).all()
    assert np.median(m_easy) > 2 * np.median(m_hard), (
        f"margins do not separate: easy {np.median(m_easy):.3f} vs "
        f"hard {np.median(m_hard):.3f}")


def test_margins_shared_with_ivf_pq(clustered):
    x, easy, _, _ = clustered
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=N_LISTS, pq_dim=DIM,
                           kmeans_n_iters=4), x)
    m = np.asarray(ivf_pq.coarse_margins(idx, easy))
    assert m.shape == (easy.shape[0],)
    assert ((0 <= m) & (m <= 1)).all()


# ---------------------------------------------------------------------------
# acceptance: recall band + probed-work reduction + (bucket, k, rung)
# trace stability
# ---------------------------------------------------------------------------


def test_adaptive_recall_band_and_trace_stability(clustered):
    x, easy, hard, index = clustered
    k = 4
    obs.set_mode("on")
    try:
        obs.reset()
        with serve.Server(_params()) as srv:       # warmup on
            srv.add_index("default", index, algo="ivf_flat", dataset=x)
            assert srv.stats()["probe_ladder"] == [1, 2, 4, 8]
            # ---- easy-dominated mix (the ISSUE-14 acceptance mix) ----
            sp_exh = ivf_flat.SearchParams(
                n_probes=N_LISTS, compute_dtype="f32",
                local_recall_target=1.0)
            mix = [easy[i:i + 1] for i in range(24)] + [hard[:1]]
            # the exhaustive oracle traces its own (unpadded, unfiltered)
            # shapes — keep it out of the serve trace-stability window
            exhaustive = [np.asarray(ivf_flat.search(sp_exh, index,
                                                     q, k)[1])
                          for q in mix]
            before = serve.trace_cache_sizes()
            served = []
            for q in mix:
                _, si = srv.search(q, k)
                served.append(np.asarray(si))
            # mutation + prefilter traffic rides the same ladder
            srv.delete([int(served[0][0, 0])])
            filt = Bitset.from_dense(np.arange(512) % 2 == 0)
            srv.search(easy[:3], 3, prefilter=filt)
            srv.search(hard[:1], 2)
            after = serve.trace_cache_sizes()
            assert after == before, (
                f"adaptive steady state retraced: {before} -> {after}")
        served = np.concatenate(served)
        exhaustive = np.concatenate(exhaustive)
        gt = np.asarray(brute_force.knn(
            np.concatenate(mix), x, k)[1])
        recall_served = float(np.mean([
            len(set(served[j]) & set(gt[j])) / k
            for j in range(gt.shape[0])]))
        recall_exh = float(np.mean([
            len(set(exhaustive[j]) & set(gt[j])) / k
            for j in range(gt.shape[0])]))
        assert recall_served >= recall_exh - 0.01, (
            f"adaptive recall {recall_served:.4f} fell below the "
            f"exhaustive band ({recall_exh:.4f} - 0.01)")
        # ---- >= 4x mean probed-list reduction on the easy mix --------
        snap = obs.snapshot(runtime_gauges=False)["metrics"]
        pts = snap["serve.probe_rung"]["points"]
        total = sum(p["value"] for p in pts)
        probed = sum(p["value"] * int(p["labels"]["rung"]) for p in pts)
        mean_probed = probed / total
        assert mean_probed <= N_LISTS / 4, (
            f"mean probed lists {mean_probed:.2f} not a 4x reduction "
            f"vs exhaustive {N_LISTS}")
        assert "serve.difficulty_margin" in snap
    finally:
        obs.set_mode(None)
        obs.reset()


def test_escape_hatch_serves_bitwise_vs_nonadaptive(clustered):
    """Ambiguous margins route to the TOP rung, which must dispatch the
    exact program the non-adaptive path runs — tombstones and user
    prefilters composed — so the escape hatch costs zero correctness."""
    x, easy, hard, index = clustered
    dead = [3, 17, 99]
    filt = Bitset.from_dense(np.arange(512) % 3 != 0)
    q = np.concatenate([easy[:2], hard[:2]])
    results = {}
    for adaptive in (True, False):
        with serve.Server(_params(warmup=False,
                                  adaptive_probes=adaptive)) as srv:
            srv.add_index("default", index, algo="ivf_flat", dataset=x)
            if adaptive:
                # force EVERY query through the escape hatch
                h = srv.registry.get("default").handle
                h.adaptive = dataclasses.replace(
                    h.adaptive, easy_margin=1.01, floor_margin=1.0)
            srv.delete(dead)
            results[adaptive] = (
                srv.search(q, 4, prefilter=filt))
    np.testing.assert_array_equal(results[True][0], results[False][0])
    np.testing.assert_array_equal(results[True][1], results[False][1])


def test_forced_rung_matches_explicit_params(clustered, monkeypatch):
    """tombstone/prefilter x adaptive composition: a mid-ladder rung
    serves bitwise what a non-adaptive server with the same explicit
    n_probes serves."""
    x, easy, hard, index = clustered
    rung = 4
    monkeypatch.setattr(AdaptivePolicy, "choose_idx",
                        lambda self, m, k=1: self.ladder.index(rung))
    dead = [5, 42]
    filt = Bitset.from_dense(np.arange(512) % 2 == 0)
    q = np.concatenate([easy[:2], hard[:2]])
    with serve.Server(_params(warmup=False)) as srv:
        srv.add_index("default", index, algo="ivf_flat", dataset=x)
        srv.delete(dead)
        ad, ai = srv.search(q, 4, prefilter=filt)
    with serve.Server(_params(warmup=False,
                              adaptive_probes=False)) as srv:
        srv.add_index(
            "default", index, algo="ivf_flat", dataset=x,
            search_params=ivf_flat.SearchParams(
                n_probes=rung, compute_dtype="f32",
                local_recall_target=1.0))
        srv.delete(dead)
        ed, ei = srv.search(q, 4, prefilter=filt)
    np.testing.assert_array_equal(ai, ei)
    np.testing.assert_array_equal(ad, ed)


def test_rabitq_pipeline_rides_adaptive_rungs(clustered):
    """The rabitq multi-stage pipeline composes with adaptive rungs:
    per-rung n_probes + the per-rung refine_ratio rung (easiest rung
    halves the over-fetch; ROADMAP item 2b)."""
    x, easy, _, _ = clustered
    bp = ivf_pq.IndexParams(n_lists=4, pq_dim=DIM, kmeans_n_iters=4,
                            cache_dtype="rabitq")
    with serve.Server(_params(warmup=False, max_k=4)) as srv:
        srv.create_index("default", x, algo="ivf_pq", build_params=bp)
        h = srv.registry.get("default").handle
        assert h.adaptive is not None
        assert h.adaptive.refine_ladder() == (2, 4)
        d, i = srv.search(easy[:3], 4)
        assert i.shape == (3, 4) and (np.asarray(i) >= 0).all()
        # a served id deletes cleanly through whatever rung serves it
        victim = int(np.asarray(i)[0, 0])
        srv.delete([victim])
        _, i2 = srv.search(easy[:3], 4)
        assert victim not in np.asarray(i2)


# ---------------------------------------------------------------------------
# deadline-driven serving
# ---------------------------------------------------------------------------


def test_deadline_request_skips_linger(clustered):
    x = clustered[0]
    with serve.Server(_params(warmup=False, max_wait_ms=400.0,
                              adaptive_probes=False)) as srv:
        srv.create_index("default", x, algo="brute_force")
        srv.search(x[0], 2)                     # compile outside timing
        t0 = time.monotonic()
        srv.search(x[1], 2, deadline_ms=150)
        took_ms = (time.monotonic() - t0) * 1e3
        # a lingering dispatcher would hold the request ~400 ms; the
        # priority lane's slack test releases it once the remaining
        # budget only just covers the service estimate + headroom
        assert took_ms < 300, f"deadline request lingered {took_ms:.0f}ms"


def test_deadline_shed_under_slow_dispatch(clustered, monkeypatch):
    from raft_tpu import resilience

    x = clustered[0]
    monkeypatch.setenv("RAFT_TPU_FAULTS_SLOW_MS", "300")
    obs.set_mode("on")
    try:
        obs.reset()
        with serve.Server(_params(warmup=False, max_wait_ms=1.0,
                                  adaptive_probes=False)) as srv:
            srv.create_index("default", x, algo="brute_force")
            srv.search(x[0], 2)                 # compile before the storm
            faultinject.install("slow@stage:serve.dispatch*20")
            futs = [srv.submit(x[j], 2, deadline_ms=50)
                    for j in range(6)]
            shed = served = 0
            for f in futs:
                try:
                    f.result(timeout=60)
                    served += 1
                except serve.Overloaded as e:
                    assert e.reason == "deadline"
                    assert resilience.classify(e) == resilience.TRANSIENT
                    shed += 1
            assert shed >= 1, "no deadline work was shed under the stall"
            faultinject.clear()
            snap = obs.snapshot(runtime_gauges=False)["metrics"]
            pts = snap["serve.deadline_shed_total"]["points"]
            assert sum(p["value"] for p in pts
                       if p["labels"]["action"] == "shed") == shed
            # the server stays healthy once the stall clears
            _, i = srv.search(x[0], 2)
            assert int(i[0, 0]) == 0
    finally:
        obs.set_mode(None)
        obs.reset()


def test_slow_stage_fault_grammar():
    specs = faultinject.parse("slow@stage:serve.dispatch*3")
    assert specs[0].kind == "slow" and specs[0].remaining == 3
    with pytest.raises(ValueError):
        faultinject.parse("slow@chunk:1")


def test_admission_quotas(clustered):
    from raft_tpu import resilience

    x = clustered[0]
    with serve.Server(_params(
            warmup=False, adaptive_probes=False, max_wait_ms=400.0,
            admission_quotas={"default": 2},
            max_total_queue_rows=8)) as srv:
        srv.create_index("default", x, algo="brute_force")
        srv.search(x[0], 2)                     # compile outside window
        futs = [srv.submit(x[0], 2), srv.submit(x[1], 2)]
        with pytest.raises(serve.Overloaded) as ei:
            srv.submit(x[2], 2)
        assert ei.value.reason == "quota"
        assert resilience.classify(ei.value) == resilience.TRANSIENT
        for f in futs:
            f.result(timeout=60)


# ---------------------------------------------------------------------------
# swap re-derivation (ISSUE-14 satellite regression)
# ---------------------------------------------------------------------------


def test_swap_rederives_probe_ladder(clustered):
    """After a same-algo swap to a bigger index, the TOP rung equals
    the new n_lists — the ladder re-derives, not just the ceiling."""
    x = clustered[0]
    rng = np.random.default_rng(31)
    big = rng.standard_normal((x.shape[0] * 4, DIM)).astype(np.float32)
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x, algo="ivf_flat")
        h0 = srv.registry.get("default").handle
        assert h0.adaptive.ladder[-1] == h0.index.n_lists
        srv.swap("default", dataset=big, wait=True)
        h1 = srv.registry.get("default").handle
        assert h1.index.n_lists > h0.index.n_lists
        assert h1.adaptive.ladder[-1] == h1.index.n_lists
        assert h1.adaptive.ladder == tuple(
            serve.probe_ladder(h1.index.n_lists))
        # an explicit user n_probes stays the ceiling across swaps
        srv.swap("default", dataset=x,
                 search_params=ivf_flat.SearchParams(n_probes=3),
                 wait=True)
        h2 = srv.registry.get("default").handle
        assert h2.adaptive.ladder == (1, 2, 3)
