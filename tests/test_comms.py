"""Collective self-tests on the 8-device CPU mesh — the analog of the
reference's comms self-test kernels invoked from Python
(comms/comms_test.hpp via raft-dask comms_utils.pyx:78-244,
test_comms.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from raft_tpu.comms.compat import shard_map

from raft_tpu.comms import Comms, local_handle, sharded_knn, sharded_pairwise_distance
from tests.oracles import eval_recall, naive_knn, naive_pairwise


def _run(mesh, fn, in_specs, out_specs, *args):
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    )(*args)


def test_allreduce(eight_device_mesh):
    comms = Comms(eight_device_mesh)
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    out = _run(eight_device_mesh, lambda s: comms.allreduce(s), (P("shard", None),), P("shard", None), x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))


def test_bcast_and_barrier(eight_device_mesh):
    comms = Comms(eight_device_mesh)
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    def f(s):
        comms.barrier()
        return comms.bcast(s, root=3)

    out = _run(eight_device_mesh, f, (P("shard", None),), P("shard", None), x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))


def test_allgather_reducescatter_sendrecv(eight_device_mesh):
    comms = Comms(eight_device_mesh)
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)

    def f(s):
        g = comms.allgather(s, axis=0, tiled=True)  # [8,2] on every shard
        rs = comms.reducescatter(g, scatter_axis=0)  # back to [1,2], x8
        shifted = comms.device_sendrecv(s, shift=1)
        return rs, shifted

    rs, shifted = _run(
        eight_device_mesh, f, (P("shard", None),), (P("shard", None), P("shard", None)), x
    )
    np.testing.assert_allclose(np.asarray(rs), np.asarray(x) * 8)
    np.testing.assert_allclose(np.asarray(shifted), np.roll(np.asarray(x), 1, axis=0))


def test_comm_split_rank(eight_device_mesh):
    h = local_handle(eight_device_mesh)
    assert h.comms.size == 8

    def f(s):
        return (h.comms.rank() + 0 * s[0, 0]).reshape(1, 1).astype(jnp.float32)

    out = _run(eight_device_mesh, f, (P("shard", None),), P("shard", None),
               jnp.zeros((8, 1), jnp.float32))
    np.testing.assert_array_equal(np.asarray(out).ravel(), np.arange(8))


def test_sharded_knn(rng, eight_device_mesh):
    n, m, d, k = 800, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    dist, idx = sharded_knn(q, x, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_sharded_ivf_search(rng, eight_device_mesh):
    from raft_tpu.comms import sharded_ivf_search
    from raft_tpu.neighbors import ivf_flat

    n, m, d, k = 2000, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    params = ivf_flat.IndexParams(
        n_lists=16, kmeans_n_iters=5, kmeans_trainset_fraction=1.0
    )
    index = ivf_flat.build(params, x)
    # full probe across shards -> exact up to list assignment: recall ~1
    sp = ivf_flat.SearchParams(
        n_probes=16, query_group=8, local_recall_target=1.0
    )
    dist, idx = sharded_ivf_search(sp, index, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_sharded_pairwise(rng, eight_device_mesh):
    x = rng.standard_normal((64, 16)).astype(np.float32)
    y = rng.standard_normal((40, 16)).astype(np.float32)
    got = np.asarray(sharded_pairwise_distance(x, y, eight_device_mesh, metric="l1"))
    want = naive_pairwise(x, y, "l1")
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_sharded_ivf_pq_search(rng, eight_device_mesh):
    from raft_tpu.comms import sharded_ivf_pq_search
    from raft_tpu.neighbors import ivf_pq

    n, m, d, k = 2048, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, pq_bits=8, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0,
    )
    index = ivf_pq.build(params, x)
    sp = ivf_pq.SearchParams(
        n_probes=16, query_group=8, local_recall_target=1.0
    )
    dist, idx = sharded_ivf_pq_search(sp, index, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    # PQ distances are approximate: recall bound mirrors test_ivf_pq
    assert eval_recall(np.asarray(idx), want) > 0.7
    # agrees with the single-device search at the same effective probes
    d1, i1 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, local_recall_target=1.0),
        index, q, k)
    assert eval_recall(np.asarray(idx), np.asarray(i1)) > 0.7


@pytest.mark.parametrize("cache", ["i4", "i8raw"])
def test_sharded_ivf_pq_search_refined(rng, eight_device_mesh, cache):
    """refine_ratio>1: per-shard exact re-rank decoded from each shard's
    OWN residual-cache shard (no raw dataset anywhere in the search+refine
    path — the DEEP-1B model where the f32 dataset can never be
    resident). Recall must not drop vs the raw sharded search. The i8raw
    variant is the SHARDED_r05.json headline config in miniature
    (attach_raw_residual_cache dtype='i8', per-list scales sharded)."""
    from raft_tpu.comms import sharded_ivf_pq_search
    from raft_tpu.neighbors import ivf_pq

    n, m, d, k = 2048, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=8, pq_bits=8, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0,
        cache_dtype="i4" if cache == "i4" else "auto",
        cache_decoded=cache == "i4",
    )
    index = ivf_pq.build(params, x)
    if cache == "i8raw":
        index = ivf_pq.attach_raw_residual_cache(index, x, block_lists=5,
                                                 dtype="i8")
        assert index.cache_kind == "i8"
        assert index.cache_scales is not None
    assert index.recon_cache is not None
    sp = ivf_pq.SearchParams(
        n_probes=16, query_group=8, local_recall_target=1.0
    )
    _, raw_idx = sharded_ivf_pq_search(sp, index, q, k, eight_device_mesh)
    _, idx = sharded_ivf_pq_search(
        sp, index, q, k, eight_device_mesh, refine_ratio=4
    )
    _, want = naive_knn(q, x, k)
    r_raw = eval_recall(np.asarray(raw_idx), want)
    r_ref = eval_recall(np.asarray(idx), want)
    assert r_ref >= r_raw - 0.02
    ii = np.asarray(idx)
    assert ((ii >= 0) & (ii < n)).all()
    # matches the single-device cache-refined search's quality
    _, i1 = ivf_pq.search_refined(
        ivf_pq.SearchParams(n_probes=16, local_recall_target=1.0),
        index, q, k, refine_ratio=4)
    assert abs(eval_recall(np.asarray(i1), want) - r_ref) < 0.1


def test_sharded_cagra_build_search(rng, eight_device_mesh):
    from raft_tpu.comms import sharded_cagra_build, sharded_cagra_search
    from raft_tpu.neighbors import cagra

    centers = rng.uniform(-5, 5, (16, 32)).astype(np.float32)
    n, m, k = 4096, 32, 10
    x = (centers[rng.integers(0, 16, n)]
         + 0.7 * rng.standard_normal((n, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 16, m)]
         + 0.7 * rng.standard_normal((m, 32))).astype(np.float32)
    params = cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16, inline_codes=False)
    sidx = sharded_cagra_build(params, x, eight_device_mesh)
    assert sidx.dataset.shape[0] == 8
    sp = cagra.SearchParams(itopk_size=64)
    dist, idx = sharded_cagra_search(sp, sidx, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.9
    # ids must be globally offset & unique per row
    ii = np.asarray(idx)
    for r in range(ii.shape[0]):
        live = ii[r][ii[r] >= 0]
        assert len(set(live.tolist())) == len(live)
        assert live.max() < n


def test_sharded_cagra_fused_beam_parity(rng, eight_device_mesh):
    """The sharded CAGRA search runs the REAL fused Pallas beam kernel
    per shard (stacked inline tables through shard_map, interpret mode
    on the CPU mesh) and must match the scattered exact path's recall —
    VERDICT r4 #6 (previously a placeholder xla_exact fallback)."""
    from raft_tpu.comms import sharded_cagra_build, sharded_cagra_search
    from raft_tpu.neighbors import cagra

    centers = rng.uniform(-5, 5, (16, 32)).astype(np.float32)
    n, m, k = 4096, 32, 10
    x = (centers[rng.integers(0, 16, n)]
         + 0.7 * rng.standard_normal((n, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 16, m)]
         + 0.7 * rng.standard_normal((m, 32))).astype(np.float32)
    params = cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16)   # inline default
    sidx = sharded_cagra_build(params, x, eight_device_mesh)
    assert sidx.nbr_pack is not None
    assert sidx.nbr_pack.shape[0] == 8
    assert sidx.flat_codes.dtype == np.int8
    sp_x = cagra.SearchParams(itopk_size=64, scan_impl="xla")
    _, i_x = sharded_cagra_search(sp_x, sidx, q, k, eight_device_mesh)
    sp_p = cagra.SearchParams(itopk_size=64, scan_impl="pallas_interpret")
    _, i_p = sharded_cagra_search(sp_p, sidx, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    r_x = eval_recall(np.asarray(i_x), want)
    r_p = eval_recall(np.asarray(i_p), want)
    assert r_x > 0.9
    # int8 traversal scoring may reorder near-ties; recall parity is the
    # contract (mirrors the single-device pallas-vs-xla parity test)
    assert r_p > r_x - 0.05, (r_p, r_x)
    ii = np.asarray(i_p)
    assert (ii < n).all()
    for r in range(ii.shape[0]):
        live = ii[r][ii[r] >= 0]
        assert len(set(live.tolist())) == len(live)


def test_sharded_ivf_build_row_search(rng, eight_device_mesh):
    from raft_tpu.comms import sharded_ivf_build, sharded_ivf_row_search
    from raft_tpu.neighbors import ivf_flat

    n, m, d, k = 4096, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    params = ivf_flat.IndexParams(
        n_lists=16, kmeans_n_iters=5, kmeans_trainset_fraction=1.0
    )
    sidx = sharded_ivf_build(params, x, eight_device_mesh)
    assert sidx.centers.shape[0] == 8
    # all shards share shard-0's coarse centers
    np.testing.assert_array_equal(np.asarray(sidx.centers[0]),
                                  np.asarray(sidx.centers[3]))
    sp = ivf_flat.SearchParams(
        n_probes=16, query_group=8, local_recall_target=1.0
    )
    dist, idx = sharded_ivf_row_search(sp, sidx, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_sharded_ivf_pq_build(rng, eight_device_mesh):
    """Row-sharded encode under shard_map produces the same index
    contents as the single-device build given identical quantizer
    training data (shared quantizers -> identical codes/bucketing)."""
    from raft_tpu.comms import sharded_ivf_pq_build, sharded_ivf_pq_search
    from raft_tpu.neighbors import ivf_pq

    n, m, d, k = 4096, 24, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, pq_bits=8, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0,
    )
    got = sharded_ivf_pq_build(params, x, eight_device_mesh)
    ref = ivf_pq.build(params, x)
    np.testing.assert_array_equal(np.asarray(got.list_sizes),
                                  np.asarray(ref.list_sizes))
    np.testing.assert_array_equal(np.asarray(got.codes),
                                  np.asarray(ref.codes))
    np.testing.assert_array_equal(np.asarray(got.indices),
                                  np.asarray(ref.indices))
    # and the built index searches correctly over the mesh
    sp = ivf_pq.SearchParams(n_probes=16, query_group=8,
                             local_recall_target=1.0)
    _, idx = sharded_ivf_pq_search(sp, got, q, k, eight_device_mesh)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.7


def test_comms_session_registry(eight_device_mesh):
    """CommsSession.init/destroy + sessionId->handle registry (reference
    raft-dask Comms, raft_dask/common/comms.py:173,248,269)."""
    import jax
    import jax.numpy as jnp
    from raft_tpu.comms.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from raft_tpu.comms import CommsSession, get_comm_state, session_handle

    with CommsSession(eight_device_mesh) as s1:
        s2 = CommsSession(eight_device_mesh).init()
        assert s1.sessionId != s2.sessionId
        h1 = session_handle(s1.sessionId)
        h2 = session_handle(s2.sessionId)
        assert h1 is not None and h2 is not None and h1 is not h2
        assert h1.comms.size == 8

        def f(x, _c=h1.comms):
            return _c.allreduce(x)

        y = jax.jit(shard_map(f, mesh=h1.mesh, in_specs=P("shard"),
                              out_specs=P()))(jnp.ones((8,), jnp.float32))
        assert float(y[0]) == 8.0
        s2.destroy()
        assert session_handle(s2.sessionId) is None
    # context exit destroyed s1
    assert get_comm_state(None).get(s1.sessionId, {}).get("handle") is None
    # double-init warns and keeps state
    s3 = CommsSession(eight_device_mesh).init()
    import warnings as _w
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        s3.init()
    assert any("already been initialized" in str(r.message) for r in rec)
    s3.destroy()
