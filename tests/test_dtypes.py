"""ANN dtype matrix — float32 / int8 / uint8 per index type, mirroring the
reference's per-dtype test instantiations
(cpp/test/neighbors/ann_ivf_flat/test_*{float,int8_t,uint8_t}*.cu,
ann_ivf_pq/..., brute_force dtype coverage)."""

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine


def _data(dtype, n=6000, d=32, nq=300, seed=0):
    rng = np.random.default_rng(seed)
    if dtype == np.float32:
        x = rng.standard_normal((n, d)).astype(np.float32) * 40 + 128
        q = rng.standard_normal((nq, d)).astype(np.float32) * 40 + 128
    elif dtype == np.uint8:
        x = rng.integers(0, 256, (n, d)).astype(np.uint8)
        q = rng.integers(0, 256, (nq, d)).astype(np.uint8)
    else:
        x = rng.integers(-128, 128, (n, d)).astype(np.int8)
        q = rng.integers(-128, 128, (nq, d)).astype(np.int8)
    return x, q


def _oracle(q, x, k):
    d = (
        (q.astype(np.float64)[:, None, :] - x.astype(np.float64)[None, :, :])
        ** 2
    ).sum(-1)
    return np.argsort(d, axis=1)[:, :k]


def _recall(found, want):
    return np.mean(
        [len(set(found[r]) & set(want[r])) / want.shape[1]
         for r in range(want.shape[0])]
    )


DTYPES = [np.float32, np.int8, np.uint8]


@pytest.mark.parametrize("dtype", DTYPES, ids=["f32", "i8", "u8"])
class TestDtypeMatrix:
    def test_brute_force(self, dtype):
        x, q = _data(dtype)
        want = _oracle(q, x, 10)
        _, idx = brute_force.knn(jnp.asarray(q), jnp.asarray(x), 10)
        assert _recall(np.asarray(idx), want) > 0.99

    def test_ivf_flat(self, dtype):
        x, q = _data(dtype)
        want = _oracle(q, x, 10)
        index = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x)
        # storage keeps the source dtype (reference ivf_flat_types.hpp:
        # the index is templated on T)
        assert index.storage.dtype == x.dtype
        _, idx = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=16, local_recall_target=1.0,
                                  compute_dtype="f32"),
            index, jnp.asarray(q), 10,
        )
        assert _recall(np.asarray(idx), want) > 0.99

    def test_ivf_pq_with_refine(self, dtype):
        x, q = _data(dtype)
        want = _oracle(q, x, 10)
        index = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16), x
        )
        _, cand = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=16), index, jnp.asarray(q), 40
        )
        # PQ alone is lossy; the reference pipeline re-ranks with refine
        _, idx = refine(jnp.asarray(x), jnp.asarray(q), cand, 10,
                        "sqeuclidean")
        assert _recall(np.asarray(idx), want) > 0.95
