"""IVF-PQ tests — reference pattern (cpp/test/neighbors/ann_ivf_pq/,
pylibraft test_ivf_pq.py): recall vs exact oracle with PQ-appropriate
bounds, refine recovery, codebook modes, serialization."""

import numpy as np
import pytest

from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_pq, refine
from tests.oracles import eval_recall, naive_knn


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    centers = rng.uniform(-5, 5, (32, 32)).astype(np.float32)
    x = (centers[rng.integers(0, 32, 6000)]
         + 0.5 * rng.standard_normal((6000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 32, 150)]
         + 0.5 * rng.standard_normal((150, 32))).astype(np.float32)
    return x, q


def _build(x, n_lists=16, pq_dim=16, pq_bits=8, **kw):
    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=pq_bits,
        kmeans_n_iters=10, **kw)
    return ivf_pq.build(params, x)


def test_build_structure(dataset):
    x, _ = dataset
    index = _build(x)
    assert index.size == x.shape[0]
    assert index.pq_dim == 16
    assert index.pq_len == 2
    assert index.rot_dim == 32
    # codes are bit-packed uint32 words: 4 codes/word at pq_bits=8
    assert index.codes.dtype == np.uint32
    assert index.codes.shape[2] == 16 // 4
    assert index.pq_centers.shape == (16, 256, 2)
    # rotation must have orthonormal columns
    R = np.asarray(index.rotation)
    np.testing.assert_allclose(R.T @ R, np.eye(32), atol=1e-4)


def test_search_recall(dataset):
    x, q = dataset
    k = 10
    # pq_dim=16 → 2x compression; quantization-limited recall ~0.73 here
    # (measured: 0.44/0.73/0.96 for pq_dim 8/16/32 — scales as expected)
    index = _build(x)
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, idx = ivf_pq.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.65


def test_streaming_build_matches_dense(dataset):
    """batch_size-streamed build (BatchLoadIterator) equals the in-core
    build: same list contents, same search results."""
    x, q = dataset
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=10)
    dense = ivf_pq.build(params, x)
    streamed = ivf_pq.build(params, np.asarray(x), batch_size=1000)
    np.testing.assert_array_equal(
        np.asarray(dense.list_sizes), np.asarray(streamed.list_sizes)
    )
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, i_d = ivf_pq.search(sp, dense, q[:50], 10)
    _, i_s = ivf_pq.search(sp, streamed, q[:50], 10)
    np.testing.assert_array_equal(np.asarray(i_d), np.asarray(i_s))


@pytest.mark.parametrize("bits", [4, 5, 6, 7, 8])
def test_pack_codes_roundtrip(bits):
    rng = np.random.default_rng(bits)
    codes = rng.integers(0, 1 << bits, (40, 16), dtype=np.uint8)
    packed = ivf_pq.pack_codes(codes, bits)
    # packed memory = pq_dim * bits / 8 bytes (+ <=4 wasted bits/word)
    cpw = 32 // bits
    assert packed.shape == (40, -(-16 // cpw))
    un = np.asarray(ivf_pq.unpack_codes(packed, 16, bits))
    np.testing.assert_array_equal(un, codes)


def test_pq_bits4_storage_is_half(dataset):
    """pq_bits=4 actually halves code storage vs pq_bits=8 (VERDICT r1:
    packed memory = n*pq_dim*pq_bits/8)."""
    x, _ = dataset
    i8 = _build(x, pq_bits=8)
    i4 = _build(x, pq_bits=4)
    assert i4.codes.shape[2] * 2 == i8.codes.shape[2] * 1  # 8/word vs 4/word


@pytest.mark.parametrize("lut,internal", [("bf16", "f32"), ("f8", "bf16")])
def test_lut_dtype_ladder(dataset, lut, internal):
    """lut_dtype / internal_distance_dtype are functional (ADVICE r1):
    lower-precision ladders trade a little recall, not correctness."""
    x, q = dataset
    k = 10
    index = _build(x)
    sp = ivf_pq.SearchParams(
        n_probes=16, query_group=64, bucket_batch=4,
        lut_dtype=lut, internal_distance_dtype=internal,
    )
    _, idx = ivf_pq.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.55


def test_search_with_refine(dataset):
    x, q = dataset
    k = 10
    index = _build(x)
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, cand = ivf_pq.search(sp, index, q, 8 * k)
    _, idx = refine(x, q, cand, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.95


@pytest.mark.parametrize("cache_dtype", ["auto", "i4"])
def test_search_refined_from_cache(dataset, cache_dtype):
    """search_refined re-ranks candidates decoded from the residual cache
    (i8 via auto, packed i4) WITHOUT touching the raw dataset — the
    refine source for cache-only / billion-scale sharded indexes
    (reference refine_ratio pattern, bench/ann raft_ivf_pq_wrapper.h +
    detail/refine_host-inl.hpp)."""
    x, q = dataset
    k = 10
    index = _build(x, cache_dtype=cache_dtype)
    assert index.recon_cache is not None
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, base_idx = ivf_pq.search(sp, index, q, k)
    d, idx = ivf_pq.search_refined(sp, index, q, k, refine_ratio=4)
    _, want = naive_knn(q, x, k)
    r_base = eval_recall(np.asarray(base_idx), want)
    r_ref = eval_recall(np.asarray(idx), want)
    # the wider candidate pool + exact f32 re-rank never loses recall
    assert r_ref >= r_base - 0.02
    assert r_ref > 0.65
    # ids are real dataset rows (slot substitution resolved), dists sorted
    ii = np.asarray(idx)
    assert ((ii >= 0) & (ii < x.shape[0])).all()
    dd = np.asarray(d)
    assert (np.diff(dd, axis=1) >= -1e-6).all()


@pytest.mark.parametrize("cache_dtype", ["i4", "i8"])
def test_attach_raw_residual_cache_refine(dataset, cache_dtype):
    """attach_raw_residual_cache: raw rotated residuals beat the PQ codes
    as both scan operand and refine source — the DEEP-1B recipe's
    fidelity ladder (codes for capacity, raw-residual cache for ranking,
    cache-decoded f32 re-rank on top; reference refines from the raw
    dataset instead, detail/refine_host-inl.hpp). i8 (1 B/dim) must beat
    i4 (0.5 B/dim): ~16x lower quantization error."""
    x, q = dataset
    k = 10
    # pq_dim=8 on 32 dims: deliberately coarse codes (recall ~0.45)
    index = _build(x, pq_dim=8, cache_decoded=False)
    assert index.recon_cache is None
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, i_pq = ivf_pq.search(sp, index, q, k)
    index = ivf_pq.attach_raw_residual_cache(index, x, block_lists=5,
                                             dtype=cache_dtype)
    assert index.cache_kind == cache_dtype
    if cache_dtype == "i4":
        assert index.recon_cache.shape == (16, index.rot_dim // 8,
                                           index.indices.shape[1])
    else:
        assert index.recon_cache.shape == (16, index.indices.shape[1],
                                           index.rot_dim)
        assert index.recon_cache.dtype == np.int8
    _, i_raw = ivf_pq.search(sp, index, q, k)      # auto scans the cache
    _, i_ref = ivf_pq.search_refined(sp, index, q, k, refine_ratio=8)
    _, want = naive_knn(q, x, k)
    r_pq = eval_recall(np.asarray(i_pq), want)
    r_raw = eval_recall(np.asarray(i_raw), want)
    r_ref = eval_recall(np.asarray(i_ref), want)
    # raw residuals carry far more ranking information than
    # pq8-on-32-dims codes (0.25 B/dim); refine never loses
    assert r_raw > r_pq + 0.15, (r_pq, r_raw)
    assert r_ref >= r_raw - 0.02, (r_raw, r_ref)
    assert r_ref > (0.9 if cache_dtype == "i8" else 0.75), r_ref


def test_raw_i8_cache_save_load(dataset, tmp_path):
    """The per-list-scaled raw i8 cache serializes (a rebuild from codes
    would silently drop its fidelity)."""
    x, q = dataset
    index = _build(x, pq_dim=8, cache_decoded=False)
    index = ivf_pq.attach_raw_residual_cache(index, x, block_lists=5,
                                             dtype="i8")
    p = str(tmp_path / "rawi8.idx")
    ivf_pq.save(p, index)
    loaded = ivf_pq.load(p)
    assert loaded.cache_kind == "i8"
    assert loaded.cache_scales is not None
    np.testing.assert_array_equal(np.asarray(loaded.recon_cache),
                                  np.asarray(index.recon_cache))
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, i0 = ivf_pq.search_refined(sp, index, q[:30], 10, refine_ratio=4)
    _, i1 = ivf_pq.search_refined(sp, loaded, q[:30], 10, refine_ratio=4)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_search_refined_needs_cache(dataset):
    x, q = dataset
    index = _build(x, cache_decoded=False)
    sp = ivf_pq.SearchParams(n_probes=16)
    with pytest.raises(ValueError, match="cache"):
        ivf_pq.search_refined(sp, index, q, 10)


def test_per_cluster_codebooks(dataset):
    x, q = dataset
    k = 10
    index = _build(x, codebook_kind=ivf_pq.codebook_gen.PER_CLUSTER)
    assert index.pq_centers.shape[0] == index.n_lists
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, idx = ivf_pq.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.7


def test_pq_bits_4(dataset):
    x, q = dataset
    k = 10
    index = _build(x, pq_bits=4)
    assert index.pq_book_size == 16
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, cand = ivf_pq.search(sp, index, q, 10 * k)
    _, idx = refine(x, q, cand, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.8


def test_inner_product(dataset):
    x, q = dataset
    k = 10
    index = _build(x, metric="inner_product")
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, idx = ivf_pq.search(sp, index, q, k)
    _, want = naive_knn(q, x, k, "inner_product")
    assert eval_recall(np.asarray(idx), want) > 0.55


def test_prefilter(dataset):
    x, q = dataset
    k = 10
    n = x.shape[0]
    index = _build(x)
    allowed = np.zeros(n, bool)
    allowed[: n // 4] = True
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, idx = ivf_pq.search(sp, index, q, k, prefilter=Bitset.from_dense(allowed))
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < n // 4)).all()


def test_extend(dataset):
    x, q = dataset
    index = _build(x[:3000])
    index = ivf_pq.extend(index, x[3000:])
    assert index.size == x.shape[0]
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, cand = ivf_pq.search(sp, index, q, 80)
    _, idx = refine(x, q, cand, 10)
    _, want = naive_knn(q, x, 10)
    assert eval_recall(np.asarray(idx), want) > 0.9


def test_extend_then_prefilter(dataset):
    """extend × prefilter (ISSUE 5 satellite): a filter built BEFORE the
    extend applies afterwards — default "drop" rejects the appended
    rows, out_of_range="keep" admits them (tombstone semantics over an
    extended index)."""
    from raft_tpu.neighbors.common import BitsetFilter

    x, q = dataset
    k = 10
    n_old = 3000
    index = _build(x[:n_old])
    allowed = np.zeros(n_old, bool)
    allowed[: n_old // 2] = True
    bits = Bitset.from_dense(allowed)          # narrower than the index
    index = ivf_pq.extend(index, x[n_old:])
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)

    # default drop: only kept OLD rows can surface
    _, idx = ivf_pq.search(sp, index, q, k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < n_old // 2)).all()
    _, cand = ivf_pq.search(sp, index, q, 80, prefilter=bits)
    _, ref = refine(x, q, cand, k)
    _, want = naive_knn(q, x[: n_old // 2], k)
    assert eval_recall(np.asarray(ref), want) > 0.9

    # keep: appended rows join the allowed set
    keep_filt = BitsetFilter(bits, out_of_range="keep")
    _, idx2 = ivf_pq.search(sp, index, q, k, prefilter=keep_filt)
    idx2 = np.asarray(idx2)
    assert ((idx2 == -1) | (idx2 < n_old // 2) | (idx2 >= n_old)).all()
    sub = np.concatenate([np.arange(n_old // 2),
                          np.arange(n_old, x.shape[0])])
    _, cand2 = ivf_pq.search(sp, index, q, 80, prefilter=keep_filt)
    _, ref2 = refine(x, q, cand2, k)
    _, want_sub = naive_knn(q, x[sub], k)
    assert eval_recall(np.asarray(ref2), sub[want_sub]) > 0.9


def test_serialize_roundtrip(dataset, tmp_path):
    x, q = dataset
    index = _build(x)
    p = str(tmp_path / "pq.idx")
    ivf_pq.save(p, index)
    loaded = ivf_pq.load(p)
    sp = ivf_pq.SearchParams(n_probes=8, query_group=64, bucket_batch=4)
    d1, i1 = ivf_pq.search(sp, index, q, 10)
    d2, i2 = ivf_pq.search(sp, loaded, q, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_decode_roundtrip():
    # encoding then decoding must land on the nearest codebook entries
    rng = np.random.default_rng(3)
    import jax.numpy as jnp
    from raft_tpu.neighbors.ivf_pq import _decode_gather, _encode_subspace

    p, K, ln = 4, 16, 2
    cb = jnp.asarray(rng.standard_normal((p, K, ln)), jnp.float32)
    res = jnp.asarray(rng.standard_normal((50, p, ln)), jnp.float32)
    codes = _encode_subspace(res, cb, K)
    recon = _decode_gather(codes, cb, ivf_pq.codebook_gen.PER_SUBSPACE)
    recon = np.asarray(recon).reshape(50, p, ln)
    # each reconstructed subvector is the argmin codebook entry
    d = ((np.asarray(res)[:, :, None, :] - np.asarray(cb)[None]) ** 2).sum(-1)
    want = d.argmin(-1)
    np.testing.assert_array_equal(np.asarray(codes), want)
    np.testing.assert_allclose(recon, np.asarray(cb)[np.arange(p), want], rtol=1e-6)


@pytest.mark.parametrize("metric", ["sqeuclidean", "inner_product"])
def test_pallas_cached_scan_interpret_matches_xla(dataset, metric):
    """The fused Pallas scan over the int8 decoded-residual cache
    (interpret mode on CPU) must closely agree with the XLA
    decode-then-matmul scan — the cache adds only int8 quantization on
    top of the shared PQ approximation."""
    x, q = dataset
    k = 10
    index = _build(x, metric=metric)
    assert index.recon_cache is not None
    assert index.recon_cache.shape == index.codes.shape[:2] + (index.rot_dim,)
    kw = dict(n_probes=8, query_group=64, bucket_batch=4,
              compute_dtype="f32", local_recall_target=1.0)
    d_x, i_x = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="xla", **kw), index, q[:50], k)
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="pallas_interpret", **kw),
        index, q[:50], k)
    # int8 cache reorders PQ near-ties freely (this blob set is
    # quantization-limited), so assert *recall parity* vs the exact
    # oracle rather than id-for-id agreement
    _, want = naive_knn(q[:50], x, k, metric=metric)
    rx = eval_recall(np.asarray(i_x), want)
    rp = eval_recall(np.asarray(i_p), want)
    assert rp > rx - 0.05, (rp, rx)
    # where both paths return the same id, distances must be close
    # (cache error is int8-scale, far tighter than the reference's fp8 LUT)
    same = np.asarray(i_x) == np.asarray(i_p)
    np.testing.assert_allclose(np.asarray(d_x)[same], np.asarray(d_p)[same],
                               rtol=0.15, atol=0.5)


def test_pallas_cached_scan_interpret_filter(dataset):
    x, q = dataset
    k, n = 10, dataset[0].shape[0]
    index = _build(x)
    allowed = np.zeros(n, bool)
    allowed[: n // 4] = True
    bits = Bitset.from_dense(allowed)
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64,
                             compute_dtype="f32", local_recall_target=1.0,
                             scan_impl="pallas_interpret")
    _, idx = ivf_pq.search(sp, index, q[:50], k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < n // 4)).all()


@pytest.mark.parametrize("metric", ["sqeuclidean", "inner_product"])
def test_pq4_code_scan_interpret_matches_xla(dataset, metric):
    """cache_dtype='pq4': the fused one-hot packed-CODE scan (16-pass MXU
    contraction, reference ivf_pq_compute_similarity-inl.cuh:164-185 LUT
    analog) computes EXACTLY the decode-then-matmul distances — same
    codes, same codebook, no extra quantization — so interpret-mode
    results must match the XLA scan to float tolerance."""
    x, q = dataset
    k = 10
    index = _build(x, pq_dim=16, pq_bits=4, metric=metric,
                   cache_dtype="pq4")
    assert index.cache_kind == "pq4"
    # transposed packed codes: [C, nw, cap] vs codes [C, cap, nw]
    assert index.recon_cache.shape == (
        index.codes.shape[0], index.codes.shape[2], index.codes.shape[1])
    kw = dict(n_probes=8, query_group=64, bucket_batch=4,
              compute_dtype="f32", local_recall_target=1.0)
    d_x, i_x = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="xla", lut_dtype="f32", **kw),
        index, q[:50], k)
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="pallas_interpret", **kw),
        index, q[:50], k)
    _, want = naive_knn(q[:50], x, k, metric=metric)
    rx = eval_recall(np.asarray(i_x), want)
    rp = eval_recall(np.asarray(i_p), want)
    assert rp > rx - 0.02, (rp, rx)
    same = np.asarray(i_x) == np.asarray(i_p)
    assert same.mean() > 0.9          # only exact PQ ties may reorder
    np.testing.assert_allclose(np.asarray(d_x)[same], np.asarray(d_p)[same],
                               rtol=1e-4, atol=1e-3)


def test_pq4_cache_roundtrip_and_guards(dataset, tmp_path):
    """pq4 cache rebuilds from codes on load (never serialized); residual
    refine correctly refuses the code cache."""
    x, q = dataset
    index = _build(x, pq_dim=16, pq_bits=4, cache_dtype="pq4")
    p = str(tmp_path / "pq4.idx")
    ivf_pq.save(p, index)
    loaded = ivf_pq.load(p)
    assert loaded.cache_kind == "pq4"
    np.testing.assert_array_equal(
        np.asarray(loaded.recon_cache), np.asarray(index.recon_cache))
    sp = ivf_pq.SearchParams(n_probes=8, query_group=64, bucket_batch=4)
    _, i0 = ivf_pq.search(sp, index, q[:30], 10)
    _, i1 = ivf_pq.search(sp, loaded, q[:30], 10)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    with pytest.raises(ValueError, match="rerank source"):
        ivf_pq.search_refined(sp, index, q[:10], 10)
    # ... but an explicit dataset IS a finer source for pq4 too
    d_ds, i_ds = ivf_pq.search_refined(sp, index, q[:10], 10,
                                       refine_ratio=2, dataset=x)
    assert np.asarray(i_ds).shape == (10, 10)


def test_cache_disabled_matches(dataset):
    """cache_decoded=False falls back to the decode scan and the index
    carries no cache."""
    x, q = dataset
    index = _build(x, cache_decoded=False)
    assert index.recon_cache is None
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), index, q[:20], 5)
    assert np.asarray(i).shape == (20, 5)


def test_lut_dtype_f32_forces_true_decode(dataset):
    """Explicit lut_dtype='f32' must bypass the int8 cache (true decode),
    and 'i8' must require the cache."""
    x, q = dataset
    index = _build(x)
    kw = dict(n_probes=8, local_recall_target=1.0, compute_dtype="f32")
    k = 10
    d_c, i_c = ivf_pq.search(
        ivf_pq.SearchParams(lut_dtype="auto", scan_impl="xla", **kw),
        index, q[:50], k)
    d_f, i_f = ivf_pq.search(
        ivf_pq.SearchParams(lut_dtype="f32", scan_impl="xla", **kw),
        index, q[:50], k)
    # int8 cache freely reorders PQ near-ties; equal oracle recall is the
    # functional requirement
    _, want = naive_knn(q[:50], x, k)
    rc = eval_recall(np.asarray(i_c), want)
    rf = eval_recall(np.asarray(i_f), want)
    assert rc > rf - 0.05, (rc, rf)
    nocache = _build(x, cache_decoded=False)
    with pytest.raises(ValueError):
        ivf_pq.search(ivf_pq.SearchParams(lut_dtype="i8", **kw),
                      nocache, q[:5], 5)


def test_streaming_build_device_array(dataset):
    """batch_size streaming over a DEVICE-resident dataset (sliced in
    place, incl. the shifted static-shape tail window) equals the dense
    build."""
    import jax.numpy as jnp

    x, q = dataset
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=10)
    dense = ivf_pq.build(params, x)
    streamed = ivf_pq.build(params, jnp.asarray(x), batch_size=1792)  # 6000 % 1792 != 0
    np.testing.assert_array_equal(
        np.asarray(dense.list_sizes), np.asarray(streamed.list_sizes)
    )
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, i_d = ivf_pq.search(sp, dense, q[:50], 10)
    _, i_s = ivf_pq.search(sp, streamed, q[:50], 10)
    np.testing.assert_array_equal(np.asarray(i_d), np.asarray(i_s))


def test_build_streamed_matches_build():
    """Streamed (batch-generator, donated-scatter) build produces the
    same index contents as the one-shot build given identical
    quantizer training data."""
    import jax.numpy as jnp
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(9)
    n, d, bs = 5000, 32, 1024
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0,
    )
    ref = ivf_pq.build(params, x)

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x)
    np.testing.assert_array_equal(np.asarray(got.list_sizes),
                                  np.asarray(ref.list_sizes))
    np.testing.assert_array_equal(np.asarray(got.indices),
                                  np.asarray(ref.indices))
    np.testing.assert_array_equal(np.asarray(got.codes),
                                  np.asarray(ref.codes))
    # padding slots differ (build decodes code-0 padding, streamed leaves
    # zeros) but are masked by list_sizes everywhere — compare valid slots
    valid = np.asarray(got.indices) >= 0
    np.testing.assert_allclose(np.asarray(got.rec_norms)[valid],
                               np.asarray(ref.rec_norms)[valid], rtol=1e-5)
    # search parity
    sp = ivf_pq.SearchParams(n_probes=16)
    _, i1 = ivf_pq.search(sp, ref, x[:64], 5)
    _, i2 = ivf_pq.search(sp, got, x[:64], 5)
    assert (np.asarray(i1) == np.asarray(i2)).mean() > 0.95


def test_build_streamed_cache_only():
    """keep_codes=False: cache-only index searches via the fused scan;
    decode paths are rejected with a clear error."""
    import jax.numpy as jnp
    import pytest
    from raft_tpu.neighbors import ivf_pq
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(10)
    n, d, bs, k = 5000, 32, 1024, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0, cache_decoded=True,
    )

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                keep_codes=False)
    assert got.codes.shape[2] == 0 and got.recon_cache is not None
    q = x[:128]
    sp = ivf_pq.SearchParams(n_probes=16, scan_impl="pallas_interpret")
    _, idx = ivf_pq.search(sp, got, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.7
    with pytest.raises(ValueError, match="keep_codes=False"):
        ivf_pq.search(ivf_pq.SearchParams(n_probes=16, lut_dtype="f32"),
                      got, q, k)


def test_i4_quant_pack_roundtrip():
    """Signed-nibble pack/unpack round trip + dequantized norms."""
    import jax.numpy as jnp
    from raft_tpu.neighbors.ivf_pq import _quant_pack_i4, unpack_i4

    rng = np.random.default_rng(11)
    rot = 32
    recon = rng.standard_normal((40, rot)).astype(np.float32)
    scales = jnp.asarray(np.abs(recon).max(0) / 7.0 + 1e-9)
    packed, qnorm = _quant_pack_i4(jnp.asarray(recon), scales)
    assert packed.shape == (40, rot // 8) and packed.dtype == np.uint32
    raw = np.asarray(unpack_i4(packed))
    want = np.clip(np.round(recon / np.asarray(scales)), -8, 7)
    np.testing.assert_array_equal(raw, want)
    deq = raw * np.asarray(scales)
    np.testing.assert_allclose(np.asarray(qnorm), (deq * deq).sum(-1),
                               rtol=1e-5)


def test_i4_cache_search(dataset):
    """cache_dtype='i4': packed transposed cache, XLA and Pallas-interpret
    scans agree with the oracle at near-i8 recall."""
    x, q = dataset
    k = 10
    index = _build(x, cache_dtype="i4")
    assert index.recon_cache is not None
    assert index.recon_cache.dtype == np.uint32
    C, cap = index.indices.shape
    assert index.recon_cache.shape == (C, index.rot_dim // 8, cap)
    assert index.cache_scales.shape == (C, index.rot_dim)
    assert index.cache_qnorms.shape == (C, cap)
    kw = dict(n_probes=16, query_group=64, bucket_batch=4,
              compute_dtype="f32", local_recall_target=1.0)
    _, want = naive_knn(q, x, k)
    _, i_x = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="xla", **kw), index, q, k)
    _, i_p = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="pallas_interpret", **kw), index, q, k)
    i8 = _build(x)  # auto -> i8 at this size
    _, i_8 = ivf_pq.search(
        ivf_pq.SearchParams(scan_impl="xla", **kw), i8, q, k)
    r_x = eval_recall(np.asarray(i_x), want)
    r_p = eval_recall(np.asarray(i_p), want)
    r_8 = eval_recall(np.asarray(i_8), want)
    # int4 costs measurable recall on this adversarial wide-range blob set
    # (measured 0.68 vs 0.75 with per-list scales; ~0.03 on DEEP-like
    # manifolds) — the capacity trade the i4 cache exists for. The
    # correctness property is XLA/Pallas agreement, asserted tightly.
    assert r_x > r_8 - 0.10, (r_x, r_8)
    assert abs(r_p - r_x) < 0.03, (r_p, r_x)


def test_i4_cache_inner_product(dataset):
    x, q = dataset
    k = 10
    index = _build(x, metric="inner_product", cache_dtype="i4")
    assert index.recon_cache is not None
    sp = ivf_pq.SearchParams(n_probes=16, query_group=64, bucket_batch=4,
                             scan_impl="pallas_interpret")
    _, idx = ivf_pq.search(sp, index, q, k)
    _, want = naive_knn(q, x, k, "inner_product")
    assert eval_recall(np.asarray(idx), want) > 0.5


def test_build_streamed_cache_only_i4():
    """Streamed keep_codes=False with the int4 cache: transposed
    element-scatter accumulator matches the batch-built cache, and the
    save/load round trip preserves search results (round-3 advisor: the
    cache-only round trip silently returned wrong results)."""
    import tempfile, os
    import jax.numpy as jnp

    rng = np.random.default_rng(12)
    n, d, bs, k = 5000, 32, 1024, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0, cache_dtype="i4",
    )

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                keep_codes=False)
    assert got.codes.shape[2] == 0
    assert got.recon_cache.dtype == np.uint32
    assert got.cache_scales.shape == (16, got.rot_dim)
    # streamed transposed element-scatter lands each word in the right
    # [C, nw, cap] slot: spot-check by dequantizing one valid row and
    # comparing against the quantization of its decoded reconstruction
    from raft_tpu.neighbors.ivf_pq import unpack_i4
    ids = np.asarray(got.indices)
    l0 = int(np.argmax(np.asarray(got.list_sizes)))
    row = np.asarray(
        unpack_i4(np.asarray(got.recon_cache)[l0].T[0])  # first slot
    )
    assert row.shape == (got.rot_dim,) and np.abs(row).max() <= 8
    q = x[:128]
    sp = ivf_pq.SearchParams(n_probes=16, scan_impl="pallas_interpret")
    _, idx = ivf_pq.search(sp, got, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.65
    # cache-resident refine works on a CACHE-ONLY index (the DEEP-100M
    # scripted path): slot substitution + f32 re-rank from the i4 cache
    _, idx_r = ivf_pq.search_refined(sp, got, q, k, refine_ratio=3)
    r_plain = eval_recall(np.asarray(idx), want)
    r_ref = eval_recall(np.asarray(idx_r), want)
    assert r_ref >= r_plain - 0.02, (r_plain, r_ref)
    ii = np.asarray(idx_r)
    assert ((ii >= -1) & (ii < n)).all()
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "pq_i4.idx")
        ivf_pq.save(p, got)
        loaded = ivf_pq.load(p)
        assert loaded.recon_cache is not None
        _, idx2 = ivf_pq.search(sp, loaded, q, k)
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(idx2))


def test_cache_only_save_load_i8():
    """i8 cache-only round trip (the round-3 advisor's medium finding)."""
    import tempfile, os
    import jax.numpy as jnp

    rng = np.random.default_rng(13)
    n, d, bs, k = 4000, 32, 1024, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0,
    )

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                keep_codes=False)
    assert got.codes.shape[2] == 0 and got.recon_cache.dtype == np.int8
    q = x[:64]
    sp = ivf_pq.SearchParams(n_probes=16, scan_impl="pallas_interpret")
    _, i1 = ivf_pq.search(sp, got, q, k)
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "pq_i8.idx")
        ivf_pq.save(p, got)
        loaded = ivf_pq.load(p)
        _, i2 = ivf_pq.search(sp, loaded, q, k)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_cache_only_extend_raises():
    import jax.numpy as jnp

    rng = np.random.default_rng(14)
    n, d, bs = 3000, 32, 1024
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=5,
                                kmeans_trainset_fraction=1.0)

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                keep_codes=False)
    with pytest.raises(ValueError, match="cache-only"):
        ivf_pq.extend(got, x[:10])


def test_fused_scan_packed_i4_kernel_oracle():
    """ops/ivf_scan packed_i4 mode (interpret) against a direct numpy
    oracle: nibble unpack + scaled dot + norms must reproduce exact L2
    rankings of the dequantized vectors."""
    import jax.numpy as jnp
    from raft_tpu.neighbors.ivf_pq import _quant_pack_i4, unpack_i4
    from raft_tpu.ops import ivf_scan

    rng = np.random.default_rng(21)
    C, cap, rot, G, k = 3, 128, 16, 8, 5
    vecs = rng.standard_normal((C, cap, rot)).astype(np.float32)
    scales = (np.abs(vecs).max(axis=(0, 1)) / 7.0 + 1e-6).astype(np.float32)
    packed, qnorm = _quant_pack_i4(jnp.asarray(vecs), jnp.asarray(scales))
    storage_t = jnp.swapaxes(packed, 1, 2)          # [C, rot//8, cap]
    deq = np.asarray(unpack_i4(packed)) * scales    # [C, cap, rot]

    indices = jnp.arange(C * cap, dtype=jnp.int32).reshape(C, cap)
    sizes = jnp.full((C,), cap, jnp.int32)
    bl = jnp.asarray([2, 0, 1], jnp.int32)
    q = rng.standard_normal((3, G, rot)).astype(np.float32)
    qv = jnp.asarray(q * scales[None, None, :], jnp.float32)
    qaux = jnp.asarray((q * q).sum(-1), jnp.float32)
    norms = jnp.asarray((deq * deq).sum(-1), jnp.float32)

    out_d, out_i = ivf_scan.fused_list_scan_topk(
        storage_t, indices, sizes, bl, qv, qaux, norms, None,
        k=k, metric_kind=ivf_scan.L2, approx=False, interpret=True,
        packed_i4=True,
    )
    out_d, out_i = np.asarray(out_d), np.asarray(out_i)
    for b, lid in enumerate([2, 0, 1]):
        d2 = ((q[b][:, None, :] - deq[lid][None, :, :]) ** 2).sum(-1)
        order = np.argsort(d2, axis=1)[:, :k]
        want_i = np.asarray(indices)[lid][order]
        np.testing.assert_array_equal(out_i[b], want_i)
        np.testing.assert_allclose(
            out_d[b], np.sort(d2, axis=1)[:, :k], rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# rabitq sign-bit rung + multi-stage rerank pipeline (ISSUE 11)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dataset96():
    """The rabitq acceptance dataset: 96-dim blobs with queries drawn as
    perturbed data rows (the realistic ANN shape — a query sits near its
    true neighbors, so distance gaps exist for the 1-bit estimator to
    resolve; pure-noise queries at low dim are the known-hostile regime,
    docs/kernels.md §rabitq)."""
    rng = np.random.default_rng(11)
    n, d = 4000, 96
    centers = rng.uniform(-5, 5, (32, d)).astype(np.float32)
    x = (centers[rng.integers(0, 32, n)]
         + rng.standard_normal((n, d))).astype(np.float32)
    qi = rng.integers(0, n, 100)
    q = (x[qi] + 0.3 * rng.standard_normal((100, d))).astype(np.float32)
    return x, q


def test_pack_sign_bits_roundtrip():
    """Sign-bit pack/unpack at word-aligned AND partial-last-word dims."""
    rng = np.random.default_rng(3)
    for d in (64, 48, 33):
        v = rng.standard_normal((5, d)).astype(np.float32)
        packed = np.asarray(ivf_pq.pack_sign_bits(v))
        assert packed.shape == (5, -(-d // 32))
        signs = np.asarray(ivf_pq.unpack_sign_bits(packed, d))
        np.testing.assert_array_equal(signs, np.where(v > 0, 1.0, -1.0))


def test_rabitq_estimator_scalars():
    """fac = ||r||²/||r||₁ and <r̂, r> = ||r||² exactly (the RaBitQ
    collinearity correction)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    r = rng.standard_normal((16, 40)).astype(np.float32)
    packed, fac, n2 = ivf_pq._quant_pack_rabitq(jnp.asarray(r))
    signs = np.asarray(ivf_pq.unpack_sign_bits(packed, 40))
    rhat = np.asarray(fac)[:, None] * signs
    np.testing.assert_allclose((rhat * r).sum(1), np.asarray(n2),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(n2), (r * r).sum(1), rtol=1e-5)


def test_rabitq_cache_build(dataset):
    x, q = dataset
    index = _build(x, cache_dtype="rabitq")
    assert index.cache_kind == "rabitq"
    C, cap = index.indices.shape
    nwb = -(-index.rot_dim // 32)
    assert index.recon_cache.shape == (C, nwb, cap)
    assert index.recon_cache.dtype == np.uint32
    assert index.cache_fac.shape == (C, cap)
    assert index.cache_qnorms.shape == (C, cap)


@pytest.mark.parametrize("metric", ["sqeuclidean", "inner_product"])
def test_rabitq_scan_interpret_matches_xla(dataset, metric):
    """The Pallas packed_bits arm and the XLA estimator scan are two
    implementations of the same estimator — rankings must agree."""
    x, q = dataset
    index = _build(x, metric=metric, cache_dtype="rabitq")
    sp_x = ivf_pq.SearchParams(n_probes=16, scan_impl="xla",
                               local_recall_target=1.0)
    sp_p = ivf_pq.SearchParams(n_probes=16, scan_impl="pallas_interpret",
                               local_recall_target=1.0)
    _, ix_ = ivf_pq.search(sp_x, index, q[:64], 10)
    _, ip_ = ivf_pq.search(sp_p, index, q[:64], 10)
    ix_, ip_ = np.asarray(ix_), np.asarray(ip_)
    # the two paths round differently (the XLA body casts ±fac rows to
    # bf16; the kernel scales f32 dots by fac after the ±1 matmul), so
    # judge rankings as SETS: the estimator's dense near-ties reorder
    # exact positions without selection consequence
    overlap = np.mean([len(np.intersect1d(a, b)) / len(b)
                       for a, b in zip(ix_, ip_)])
    assert overlap > 0.8, overlap
    _, want = naive_knn(q[:64], x, 10, metric=metric)
    r_x = eval_recall(ix_, want)
    r_p = eval_recall(ip_, want)
    assert abs(r_x - r_p) < 0.05, (r_x, r_p)


def test_rabitq_pipeline_recall_band(dataset96):
    """ISSUE 11 acceptance: first stage + exact rerank matches the i4
    rung's recall band (within 0.01) at refine_ratio <= 4."""
    x, q = dataset96
    k = 64
    _, want = naive_knn(q, x, k)
    sp = ivf_pq.SearchParams(n_probes=16)
    rbq = ivf_pq.build(ivf_pq.IndexParams(
        n_lists=16, pq_dim=48, kmeans_n_iters=6, cache_dtype="rabitq"), x)
    i4 = ivf_pq.build(ivf_pq.IndexParams(
        n_lists=16, pq_dim=48, kmeans_n_iters=6, cache_dtype="i4"), x)
    _, ids_i4 = ivf_pq.search(sp, i4, q, k)
    r_i4 = eval_recall(np.asarray(ids_i4), want)
    _, ids_rb = ivf_pq.search_refined(sp, rbq, q, k, refine_ratio=4)
    r_rb = eval_recall(np.asarray(ids_rb), want)
    assert r_rb > r_i4 - 0.01, (r_rb, r_i4)
    # the first stage alone is NOT in the band — the pipeline is the rung
    _, ids_s1 = ivf_pq.search(sp, rbq, q, k)
    assert eval_recall(np.asarray(ids_s1), want) < r_rb


def test_rabitq_bytes_ladder():
    """The rows-per-HBM-byte ladder figure: rabitq's quantized payload
    is >= 4x smaller than i4's (exactly 4x at word-aligned rot), and
    the honest total (scalars + id row included) still >= 2x."""
    for rot in (64, 96, 128):
        i4_code, i4_total = ivf_pq.scan_bytes_per_row("i4", rot)
        rb_code, rb_total = ivf_pq.scan_bytes_per_row("rabitq", rot)
        assert i4_code >= 4 * rb_code, (rot, i4_code, rb_code)
        assert i4_total >= 2 * rb_total, (rot, i4_total, rb_total)


def test_rabitq_prefilter_composes(dataset):
    """Tombstone/user bitsets compose with the FIRST stage: filtered
    ids never reach the shortlist or the reranked answer."""
    x, q = dataset
    index = _build(x, cache_dtype="rabitq")
    sp = ivf_pq.SearchParams(n_probes=16)
    _, base = ivf_pq.search_refined(sp, index, q[:50], 10, refine_ratio=4)
    banned = set(np.asarray(base)[:, 0].tolist()) - {-1}
    bits = Bitset(x.shape[0])
    bits = bits.set(np.asarray(sorted(banned), np.int32), False)
    _, got = ivf_pq.search_refined(sp, index, q[:50], 10, refine_ratio=4,
                                   prefilter=bits)
    got = np.asarray(got)
    assert not (set(got[got >= 0].ravel().tolist()) & banned)
    # and the same filter composes with the dataset-rerank path
    _, got2 = ivf_pq.search_refined(sp, index, q[:50], 10, refine_ratio=4,
                                    prefilter=bits, dataset=x)
    got2 = np.asarray(got2)
    assert not (set(got2[got2 >= 0].ravel().tolist()) & banned)


def test_rabitq_dataset_rerank_beats_codes(dataset96):
    """dataset= reranks from the f32 originals — at least as good as
    the PQ-codes rerank."""
    x, q = dataset96
    k = 10
    _, want = naive_knn(q, x, k)
    index = ivf_pq.build(ivf_pq.IndexParams(
        n_lists=16, pq_dim=48, kmeans_n_iters=6, cache_dtype="rabitq"), x)
    sp = ivf_pq.SearchParams(n_probes=16)
    _, i_codes = ivf_pq.search_refined(sp, index, q, k, refine_ratio=4)
    _, i_ds = ivf_pq.search_refined(sp, index, q, k, refine_ratio=4,
                                    dataset=x)
    r_codes = eval_recall(np.asarray(i_codes), want)
    r_ds = eval_recall(np.asarray(i_ds), want)
    assert r_ds >= r_codes - 0.02, (r_ds, r_codes)


def test_rabitq_save_load(dataset, tmp_path):
    """The sign-bit cache + fac/norm sidecars survive serialization
    (streamed builds binarize RAW residuals — a rebuild from codes
    would lose that, so the cache is always serialized)."""
    x, q = dataset
    index = _build(x, cache_dtype="rabitq")
    p = str(tmp_path / "rbq.idx")
    ivf_pq.save(p, index)
    loaded = ivf_pq.load(p)
    assert loaded.cache_kind == "rabitq"
    np.testing.assert_array_equal(np.asarray(loaded.recon_cache),
                                  np.asarray(index.recon_cache))
    np.testing.assert_array_equal(np.asarray(loaded.cache_fac),
                                  np.asarray(index.cache_fac))
    sp = ivf_pq.SearchParams(n_probes=8)
    _, i0 = ivf_pq.search_refined(sp, index, q[:30], 10, refine_ratio=2)
    _, i1 = ivf_pq.search_refined(sp, loaded, q[:30], 10, refine_ratio=2)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_rabitq_extend_rebuilds_cache(dataset):
    x, q = dataset
    index = _build(x[:5000], cache_dtype="rabitq")
    bigger = ivf_pq.extend(index, x[5000:])
    assert bigger.cache_kind == "rabitq"
    assert int(bigger.size) == x.shape[0]
    sp = ivf_pq.SearchParams(n_probes=16)
    _, ids = ivf_pq.search_refined(sp, bigger, q[:30], 10, refine_ratio=4)
    assert np.asarray(ids).max() >= 5000  # new rows reachable


def test_attach_rabitq_cache_swaps_rung(dataset):
    x, q = dataset
    index = _build(x, cache_dtype="i8")
    assert index.cache_kind == "i8"
    rbq = ivf_pq.attach_rabitq_cache(index)
    assert rbq.cache_kind == "rabitq"
    sp = ivf_pq.SearchParams(n_probes=16)
    _, ids = ivf_pq.search_refined(sp, rbq, q[:30], 10, refine_ratio=4)
    assert np.asarray(ids).shape == (30, 10)


def test_rabitq_streamed_build():
    """build_streamed handles the rabitq cache-kind honestly (ISSUE 11
    satellite): streamed scatter of sign codes + fac/norm scalars, both
    keep_codes modes; the streamed cache binarizes the RAW residual."""
    import jax.numpy as jnp
    from tests.oracles import naive_knn, eval_recall

    rng = np.random.default_rng(13)
    n, d, bs, k = 5000, 64, 1024, 10
    centers = rng.uniform(-4, 4, (16, d)).astype(np.float32)
    x = (centers[rng.integers(0, 16, n)]
         + rng.standard_normal((n, d))).astype(np.float32)
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=32, kmeans_n_iters=5,
        kmeans_trainset_fraction=1.0, cache_dtype="rabitq",
    )

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    # keep_codes=True: codes + sign cache + separate qnorms
    got = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x)
    assert got.cache_kind == "rabitq"
    assert got.cache_qnorms is not None and got.cache_fac is not None
    q = x[:100] + 0.3 * rng.standard_normal((100, d)).astype(np.float32)
    sp = ivf_pq.SearchParams(n_probes=16)
    _, ids = ivf_pq.search_refined(sp, got, q, k, refine_ratio=4)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(ids), want) > 0.6
    # keep_codes=False: cache-only — first stage serves from the cache,
    # rerank needs an explicit dataset (no finer source on the index)
    got2 = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                 keep_codes=False)
    assert got2.cache_kind == "rabitq" and got2.codes.shape[-1] == 0
    _, ids2 = ivf_pq.search(sp, got2, q, k)
    assert np.asarray(ids2).shape == (100, k)
    with pytest.raises(ValueError, match="rerank source"):
        ivf_pq.search_refined(sp, got2, q, k, refine_ratio=4)
    _, ids3 = ivf_pq.search_refined(sp, got2, q, k, refine_ratio=4,
                                    dataset=x)
    assert eval_recall(np.asarray(ids3), want) > 0.6


def test_rabitq_slot_prefilter_invalidates_on_mutation(dataset):
    """Review fix (r10): a keep-mode filter narrower than the index
    materializes at _version == 1 every time, so the slot-filter cache
    must key on the SOURCE bitset's version — mutating the filter
    between pipeline calls must evict the cached slot translation."""
    from raft_tpu.neighbors.common import BitsetFilter

    x, q = dataset
    index = _build(x[:5000], cache_dtype="rabitq")
    index = ivf_pq.extend(index, x[5000:])        # filter narrower than n
    sp = ivf_pq.SearchParams(n_probes=16)
    bits = Bitset(5000)                           # keep-mode: new rows kept
    filt = BitsetFilter(bits, out_of_range="keep")
    _, i0 = ivf_pq.search_refined(sp, index, q[:40], 10, refine_ratio=4,
                                  prefilter=filt)
    victim = int(np.asarray(i0)[0, 0])
    if victim >= 5000:                            # pick an in-range id
        cand = np.asarray(i0).ravel()
        victim = int(cand[(cand >= 0) & (cand < 5000)][0])
    bits.set(np.asarray([victim], np.int32), False)   # in-place mutation
    _, i1 = ivf_pq.search_refined(sp, index, q[:40], 10, refine_ratio=4,
                                  prefilter=filt)
    assert victim not in np.asarray(i1), "stale cached slot filter served"
