import io

import numpy as np
import jax.numpy as jnp

from raft_tpu.core import (
    Bitset,
    DeviceResources,
    deserialize_mdspan,
    serialize_mdspan,
)
from raft_tpu.core.resources import get_device_resources
from raft_tpu.core.serialize import read_index_file, write_index_file


def test_resources_lazy_slots():
    h = DeviceResources(seed=7)
    k1 = h.rng_key()
    k2 = h.rng_key()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    h.set_workspace_limit(123)
    assert h.workspace_limit == 123


def test_default_handle_pool():
    h1 = get_device_resources()
    h2 = get_device_resources()
    assert h1 is h2


def test_serialize_roundtrip(rng, tmp_path):
    arr = rng.standard_normal((5, 7)).astype(np.float32)
    buf = io.BytesIO()
    serialize_mdspan(buf, arr)
    buf.seek(0)
    out = deserialize_mdspan(buf)
    np.testing.assert_array_equal(arr, out)

    p = str(tmp_path / "idx.bin")
    write_index_file(p, "test_index", 3, {"metric": "l2"}, {"a": arr, "b": np.arange(4)})
    version, meta, arrays = read_index_file(p, "test_index")
    assert version == 3 and meta["metric"] == "l2"
    np.testing.assert_array_equal(arrays["a"], arr)


def test_bitset(rng):
    n = 100
    bs = Bitset(n, default=False)
    assert int(bs.count()) == 0
    idx = jnp.asarray([0, 5, 31, 32, 63, 99])
    bs.set(idx, True)
    assert int(bs.count()) == 6
    tested = np.asarray(bs.test(jnp.asarray([0, 1, 5, 99, 98])))
    np.testing.assert_array_equal(tested, [True, False, True, True, False])
    bs.flip()
    assert int(bs.count()) == n - 6


def test_bitset_from_dense(rng):
    mask = rng.random(77) < 0.5
    bs = Bitset.from_dense(mask)
    np.testing.assert_array_equal(np.asarray(bs.to_dense()), mask)
    assert int(bs.count()) == mask.sum()


def test_bitset_resize_grow_keep(rng):
    """resize(n, default=True): new bits (incl. the old last word's tail
    bits) default to SET — the tombstone-over-extend contract (ISSUE 5)."""
    mask = rng.random(77) < 0.5          # 77: mid-word tail
    bs = Bitset.from_dense(mask).resize(200, default=True)
    assert bs.n_bits == 200
    want = np.concatenate([mask, np.ones(123, bool)])
    np.testing.assert_array_equal(np.asarray(bs.to_dense()), want)
    assert int(bs.count()) == want.sum()


def test_bitset_resize_grow_drop_and_shrink(rng):
    mask = rng.random(64) < 0.5          # word-aligned boundary too
    bs = Bitset.from_dense(mask).resize(150, default=False)
    want = np.concatenate([mask, np.zeros(86, bool)])
    np.testing.assert_array_equal(np.asarray(bs.to_dense()), want)
    # shrink truncates
    bs.resize(40)
    np.testing.assert_array_equal(np.asarray(bs.to_dense()), mask[:40])
    assert int(bs.count()) == mask[:40].sum()


def test_bitset_copy_is_independent(rng):
    mask = rng.random(50) < 0.5
    a = Bitset.from_dense(mask)
    b = a.copy()
    b.set(jnp.arange(50), False)
    np.testing.assert_array_equal(np.asarray(a.to_dense()), mask)
    assert int(b.count()) == 0


def test_bitset_count_bits_functional(rng):
    mask = rng.random(70) < 0.5
    bs = Bitset.from_dense(mask)
    # raw-word functional count matches, incl. under jit
    assert int(Bitset.count_bits(bs.bits, 70)) == mask.sum()
    import jax

    jitted = jax.jit(Bitset.count_bits, static_argnums=(1,))
    assert int(jitted(bs.bits, 70)) == mask.sum()


def test_interruptible_cancel_unblocks_sync():
    """interruptible: cancel from another thread makes the target's next
    synchronize raise (reference core/interruptible.hpp:39-105)."""
    import threading
    import time as _time

    import pytest
    import jax.numpy as jnp
    from raft_tpu.core.interruptible import (
        Interruptible, InterruptedException, cancel, synchronize,
    )

    # one-shot check(): set -> raise -> cleared
    tok = Interruptible.get_token()
    tok.cancel()
    with pytest.raises(InterruptedException):
        tok.check()
    tok.check()  # flag cleared: no raise

    # cross-thread cancel during a (long-ish) wait loop
    main_tid = threading.get_ident()
    state = {}

    def killer():
        _time.sleep(0.05)
        cancel(main_tid)

    t = threading.Thread(target=killer)
    t.start()
    # poll a ready array repeatedly so the canceller has a window; the
    # cancel lands between synchronize calls and the next one raises
    x = jnp.ones((4,))
    with pytest.raises(InterruptedException):
        for _ in range(500):
            synchronize(x)
            _time.sleep(0.001)
    t.join()
