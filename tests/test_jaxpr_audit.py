"""graft-lint engine 2 (jaxpr) tests: the entry-point registry traces on
CPU with zero findings (the tier-1 gate's second half), the auditor
catches a planted int->f32 ordering bug (regression for the ADVICE-r5
>2^24 class), f64 leaks, host callbacks, and the select_k recompile
audit passes its shape sweep."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.analysis.jaxpr_audit import (
    ENTRY_POINTS,
    _Auditor,
    audit_select_k_recompiles,
    run_audit,
)


@pytest.fixture(scope="module")
def audit():
    findings, report = run_audit()
    return findings, report


def test_registry_covers_the_public_surface():
    assert {"brute_force", "ivf_flat", "ivf_pq", "cagra", "select_k",
            "pairwise"} <= set(ENTRY_POINTS)


@pytest.mark.static_analysis
def test_gate_all_entry_points_trace_clean_on_cpu(audit):
    findings, report = audit
    open_f = [f for f in findings if not f.suppressed]
    assert not open_f, "unsuppressed jaxpr-audit findings:\n" + "\n".join(
        f.render() for f in open_f)
    assert set(report["entry_points"]) == set(ENTRY_POINTS)


@pytest.mark.static_analysis
def test_gate_recompile_audit_passes(audit):
    _, report = audit
    rec = report["recompile"]
    assert rec["status"] == "ok", rec
    assert rec["compiles_first_sweep"] >= len(rec["shapes"]) > 0
    assert rec["retraces_second_sweep"] == 0


# ---------------------------------------------------------------------------
# planted hazards (regression tests for the classes the rules encode)
# ---------------------------------------------------------------------------


def test_planted_int_to_f32_ordering_bug_is_caught():
    """The exact ADVICE-r5 class: ids above 2^24 collapse when selected
    through an f32 cast. The auditor must flag it without running any
    hot-path code."""

    def bad(x):
        ids = jnp.arange(x.shape[1], dtype=jnp.int32)
        keys = x + ids.astype(jnp.float32)
        return jax.lax.top_k(keys, 8)

    a = _Auditor("planted")
    a.walk(jax.make_jaxpr(bad)(jnp.ones((4, 64))))
    assert any(f.rule == "GL003" for f in a.findings)


def test_planted_bug_is_caught_through_jit_boundary():
    def bad(x):
        ids = jnp.arange(x.shape[1], dtype=jnp.int32)
        return jnp.argsort(ids.astype(jnp.float32) - x[0])

    a = _Auditor("planted-jit")
    a.walk(jax.make_jaxpr(jax.jit(bad))(jnp.ones((4, 64))))
    assert any(f.rule == "GL003" for f in a.findings)


def test_planted_bug_is_caught_inside_scan_body():
    def bad(xs):
        def step(carry, x):
            ids = jnp.arange(64, dtype=jnp.int32)
            _, sel = jax.lax.top_k(ids.astype(jnp.float32), 8)
            return carry, sel
        return jax.lax.scan(step, 0.0, xs)

    a = _Auditor("planted-scan")
    a.walk(jax.make_jaxpr(bad)(jnp.ones((3, 64))))
    assert any(f.rule == "GL003" for f in a.findings)


def test_clean_float_ordering_not_flagged():
    def fine(x):
        return jax.lax.top_k(-x, 8)          # float keys: the normal case

    a = _Auditor("clean")
    a.walk(jax.make_jaxpr(fine)(jnp.ones((4, 64))))
    assert not a.findings


def test_int8_decode_not_flagged():
    """int8 code decode to f32 is exact (8 bits << 24-bit mantissa) —
    the auditor must not cry wolf on the quantized scoring paths."""

    def fine(codes, k):
        return jax.lax.top_k(codes.astype(jnp.float32), k)

    a = _Auditor("int8")
    a.walk(jax.make_jaxpr(lambda c: fine(c, 4))(
        jnp.zeros((4, 64), jnp.int8)))
    assert not a.findings


def test_f64_leak_is_caught():
    x64_was = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        def leak(x):
            return x.astype(jnp.float64) * 2.0

        a = _Auditor("f64")
        a.walk(jax.make_jaxpr(leak)(jnp.ones((4,), jnp.float32)))
        assert a.f64_count > 0
    finally:
        jax.config.update("jax_enable_x64", x64_was)


def test_host_callback_is_caught():
    def cb(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((4,), jnp.float32), x)

    a = _Auditor("callback")
    a.walk(jax.make_jaxpr(cb)(jnp.ones((4,), jnp.float32)))
    assert any(f.rule == "GL001" for f in a.findings)


# ---------------------------------------------------------------------------
# recompile audit mechanics
# ---------------------------------------------------------------------------


def test_recompile_audit_counts_each_shape_once():
    findings, report = audit_select_k_recompiles(
        shapes=((2, 256), (2, 512)), k=8)
    if report["status"] == "skipped":
        pytest.skip(report["detail"])
    assert not findings
    assert report["compiles_first_sweep"] >= 2
    assert report["retraces_second_sweep"] == 0
