"""Tier-1 Pallas kernel parity gate (marker: ``pallas_parity``).

Every kernel variant runs in INTERPRET mode on CPU against the XLA
oracle — the promotion of the round-3 parity harness
(``scripts/tpu_parity.py`` / ``PALLAS_PARITY_r03.json``) into the
always-on acceptance gate: kernel regressions fail here before a chip
ever answers. Exact arms must agree BITWISE on ids with the oracle
(identical expanded-form f32 distances feed both sides, so ranking is
deterministic up to genuine ties — absent in continuous random data);
binned/fold arms must stay inside their documented recall bands
(docs/kernels.md §candidate-buffers). The on-TPU run of the same
assertions stays in scripts/tpu_parity.py (compiled-Mosaic parity);
this module is its CPU shadow.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.ops import ivf_scan
from raft_tpu.ops.fused_topk import COSINE, IP, L2, fused_topk

pytestmark = pytest.mark.pallas_parity


# ---------------------------------------------------------------------------
# fused_topk (brute-force distance + partial-top-k)
# ---------------------------------------------------------------------------


def _bf_data(rng, m=64, n=3000, d=24):
    q = rng.standard_normal((m, d)).astype(np.float32)
    x = rng.standard_normal((n, d)).astype(np.float32)
    return q, x


def _l2_dist_xla(q, x):
    """Expanded-form f32 distances through the SAME XLA ops the kernel
    runs (dot_general, f32 accumulate). A numpy/BLAS matmul here would
    sum in a different order and flip near-ties — the parity gate
    compares kernel vs XLA, not kernel vs BLAS."""
    qj, xj = jnp.asarray(q), jnp.asarray(x)
    dots = jax.lax.dot_general(
        qj, xj, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    qn = jnp.sum(qj * qj, axis=1)
    xn = jnp.sum(xj * xj, axis=1)
    return jnp.maximum(qn[:, None] + xn[None, :] - 2.0 * dots, 0.0)


def _l2_oracle(q, x, k):
    """The XLA oracle: identical expanded-form f32 distances + the
    hardware top_k — what the fused kernel must reproduce bitwise."""
    _, idx = jax.lax.top_k(-_l2_dist_xla(q, x), k)
    return np.asarray(idx)


@pytest.mark.parametrize("k", [1, 10, 100])
def test_fused_topk_exact_bitwise_ids(rng, k):
    q, x = _bf_data(rng)
    want = _l2_oracle(q, x, k)
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), k, metric_kind=L2,
                        variant="exact", interpret=True)
    np.testing.assert_array_equal(np.asarray(oi), want)


@pytest.mark.parametrize("k", [10, 200])
def test_fused_topk_fold_recall_band(rng, k):
    q, x = _bf_data(rng)
    want = _l2_oracle(q, x, k)
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), k, metric_kind=L2,
                        variant="fold", interpret=True)
    oi = np.asarray(oi)
    hits = np.mean([len(np.intersect1d(oi[i], want[i])) / k
                    for i in range(oi.shape[0])])
    # fold's per-tile loss bound is C(k, R+1)/128^R per tile — far
    # inside 1% at these shapes (the binned-path band tpu_parity uses)
    assert hits > 0.99, hits


@pytest.mark.parametrize("metric_kind", [IP, COSINE])
def test_fused_topk_ip_cosine_vs_oracle(rng, metric_kind):
    q, x = _bf_data(rng)
    k = 10
    qj, xj = jnp.asarray(q), jnp.asarray(x)
    dots = jax.lax.dot_general(
        qj, xj, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if metric_kind == IP:
        _, want = jax.lax.top_k(dots, k)
    else:
        qn = jnp.linalg.norm(qj, axis=1)[:, None]
        xn = jnp.linalg.norm(xj, axis=1)[None, :]
        cos = 1.0 - dots / jnp.maximum(qn * xn, 1e-30)
        _, want = jax.lax.top_k(-cos, k)
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), k,
                        metric_kind=metric_kind, variant="exact",
                        interpret=True)
    oi = np.asarray(oi)
    want = np.asarray(want)
    hits = np.mean([len(np.intersect1d(oi[i], want[i])) / k
                    for i in range(oi.shape[0])])
    # set-recall, not bitwise: the kernel's epilogue arithmetic
    # (fma order) may legitimately differ from the oracle's at ulp
    # scale for the division-based metrics; band leaves room for a
    # couple of boundary near-tie flips across the 640 ids
    assert hits > 0.99, hits


def test_fused_topk_pad_rows_never_selected(rng):
    """Row-tile padding (n not a multiple of tile_n) is masked to +inf
    in-kernel: pad ids must never reach the output, and rows short of k
    valid candidates return (-1, +inf)."""
    q, x = _bf_data(rng, m=16, n=700, d=16)
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), 10, metric_kind=L2,
                        variant="exact", tile_n=512, interpret=True)
    oi = np.asarray(oi)
    assert oi.max() < 700
    assert oi.min() >= 0          # 700 >= k: every slot fills

    # k > valid candidates per tile pool cannot happen (k <= n enforced
    # upstream), but short FINAL output is the n == k edge:
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x[:10]), 10,
                        metric_kind=L2, variant="exact", tile_n=512,
                        interpret=True)
    assert (np.sort(np.asarray(oi), axis=1) == np.arange(10)).all()


def test_fused_topk_fold_rejects_off_lane_tile(rng):
    """Regression (r6, graft-kern dogfood): an explicit non-lane-
    multiple tile_n reached the fold arm unvalidated and
    fold_lane_stacks silently DROPPED the tail columns from the
    reduction — rows in the dropped tail could never be returned."""
    q, x = _bf_data(rng, m=8, n=700, d=16)
    with pytest.raises(ValueError, match="tile_n % 128"):
        fused_topk(jnp.asarray(q), jnp.asarray(x), 10, metric_kind=L2,
                   variant="fold", tile_n=300, interpret=True)
    # exact arm is tail-masked per column, not lane-folded: any tile ok
    od, oi = fused_topk(jnp.asarray(q), jnp.asarray(x), 10,
                        metric_kind=L2, variant="exact", tile_n=300,
                        interpret=True)
    np.testing.assert_array_equal(np.asarray(oi), _l2_oracle(q, x, 10))


def test_tile_geometry_sublane_floor_is_dtype_aware():
    """Regression (r6, found by graft-kern's computed GL016 audit): the
    query-tile floor was a flat 8, putting the bf16 fast path's q-block
    off the (16, 128) tile at m <= 8."""
    from raft_tpu.ops.fused_topk import tile_geometry

    assert tile_geometry(4, 1000, 32, 10, "exact", itemsize=4)["tile_q"] == 8
    assert tile_geometry(4, 1000, 32, 10, "exact", itemsize=2)["tile_q"] == 16
    assert tile_geometry(8, 1000, 32, 10, "fold", itemsize=2)["tile_q"] == 16
    # 1-byte operands need the (32, 128) tile (review fix, r6)
    assert tile_geometry(4, 1000, 32, 10, "exact", itemsize=1)["tile_q"] == 32
    assert tile_geometry(200, 1000, 32, 10, "exact",
                         itemsize=2)["tile_q"] == 128


def test_fused_topk_brute_force_wiring(rng):
    """The brute_force.search impl plumbing end to end on CPU: the
    fused interpret path must return the scan path's answer (same
    distances, same ids) — the package-boundary parity check."""
    from raft_tpu.neighbors import brute_force

    q, x = _bf_data(rng, m=32, n=2000, d=16)
    ix = brute_force.build(x, "sqeuclidean")
    d_s, i_s = brute_force.search(ix, q, 10, impl="scan")
    d_f, i_f = brute_force.search(ix, q, 10,
                                  impl="fused_exact:512:interpret")
    np.testing.assert_array_equal(np.asarray(i_s), np.asarray(i_f))
    np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_f),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# fused_list_scan_topk extraction arms (IVF list scan)
# ---------------------------------------------------------------------------


def _scan_workload(rng, C=4, cap=256, d=32, G=8, nb=8):
    storage = rng.standard_normal((C, cap, d)).astype(np.float32)
    ids = (np.arange(C * cap, dtype=np.int32).reshape(C, cap))
    sizes = np.full((C,), cap, np.int32)
    buckets = (np.arange(nb, dtype=np.int32) % C)
    qv = rng.standard_normal((nb, G, d)).astype(np.float32)
    return storage, ids, sizes, buckets, qv


def _scan_oracle(storage, ids, buckets, qv, k):
    """Per-(bucket, query) exact top-k over the list block, computed
    with the kernel's own expanded-form f32 arithmetic through XLA ops
    (numpy/BLAS matmuls sum in a different order and flip near-ties)."""
    nb, G, d = qv.shape
    out = np.empty((nb, G, k), np.int64)
    for b in range(nb):
        blk = storage[buckets[b]]
        dist = np.asarray(_l2_dist_xla(qv[b], blk))
        order = np.argsort(dist, axis=1, kind="stable")[:, :k]
        out[b] = ids[buckets[b]][order]
    return out


def test_list_scan_exact_bitwise_ids(rng):
    storage, ids, sizes, buckets, qv = _scan_workload(rng)
    k = 10
    want = _scan_oracle(storage, ids, buckets, qv, k)
    qj = jnp.asarray(qv)
    qaux = jnp.sum(qj * qj, axis=2)
    norms = jnp.asarray((storage ** 2).sum(2))
    od, oi = ivf_scan.fused_list_scan_topk(
        jnp.asarray(storage), jnp.asarray(ids), jnp.asarray(sizes),
        jnp.asarray(buckets), qj, qaux, norms, None,
        k=k, metric_kind=ivf_scan.L2, approx=False, interpret=True,
        extract="exact")
    np.testing.assert_array_equal(np.asarray(oi), want)


@pytest.mark.parametrize("extract", ["binned", "binned_deep", "fold"])
def test_list_scan_binned_arms_recall_band(rng, extract):
    storage, ids, sizes, buckets, qv = _scan_workload(rng)
    k = 10 if extract == "binned" else 100
    want = _scan_oracle(storage, ids, buckets, qv, k)
    qj = jnp.asarray(qv)
    qaux = jnp.sum(qj * qj, axis=2)
    norms = jnp.asarray((storage ** 2).sum(2))
    od, oi = ivf_scan.fused_list_scan_topk(
        jnp.asarray(storage), jnp.asarray(ids), jnp.asarray(sizes),
        jnp.asarray(buckets), qj, qaux, norms, None,
        k=k, metric_kind=ivf_scan.L2, approx=True, interpret=True,
        extract=extract)
    oi = np.asarray(oi)
    if extract == "fold":
        # fold emits its R*128 buffer unextracted — select here, the
        # way the caller's cross-probe merge does
        from raft_tpu.neighbors.common import merge_topk

        nb, G, kc = oi.shape
        od2, oi2 = merge_topk(np.asarray(od).reshape(nb * G, kc),
                              oi.reshape(nb * G, kc), k, True)
        oi = np.asarray(oi2).reshape(nb, G, k)
    hits = np.mean([
        len(np.intersect1d(oi[b, g], want[b, g])) / k
        for b in range(oi.shape[0]) for g in range(oi.shape[1])
    ])
    assert hits > 0.93, (extract, hits)   # tpu_parity's binned band


def test_binned_loss_model_single_home():
    """Review fix (r6): the (k-1)/256 collision-loss model lives in ONE
    place — the entry point, the contract sweep filter, and the
    microbench candidate set all call it, so they cannot drift."""
    from raft_tpu.analysis import contracts
    from raft_tpu.ops.ivf_scan import (
        DEFAULT_RECALL_TARGET,
        binned_k_cap,
        binned_loss_fits,
    )

    assert binned_k_cap() == 13                     # 0.95 default
    assert binned_loss_fits(13) and not binned_loss_fits(14)
    assert binned_k_cap(0.8) > binned_k_cap()       # looser budget
    assert binned_loss_fits(64, recall_target=0.0)  # forcing mode
    assert DEFAULT_RECALL_TARGET == 0.95
    # the contract's binned arm tracks the model, not a constant
    c = contracts.load_all()["ivf_scan"]
    arm = next(a for a in c.arms if a.get("extract") == "binned")
    assert arm["k_max"] == binned_k_cap()


def test_list_scan_binned_eligibility_is_loss_aware(rng):
    """Regression (r6, found by the kernel-contract sweep's
    lane-boundary cases): single-slot binning loses ~(k-1)/256 of each
    list's top-k, so the old flat k <= 64 eligibility admitted ~25%
    loss at k=64 against a 0.95 per-list recall target. The entry point
    now rejects the arm when the loss model exceeds the caller's
    budget; a recall_target <= 0 (the microbench racing arms for time)
    keeps it forceable."""
    storage, ids, sizes, buckets, qv = _scan_workload(rng)
    qj = jnp.asarray(qv)
    qaux = jnp.sum(qj * qj, axis=2)
    norms = jnp.asarray((storage ** 2).sum(2))
    args = (jnp.asarray(storage), jnp.asarray(ids), jnp.asarray(sizes),
            jnp.asarray(buckets), qj, qaux, norms, None)
    with pytest.raises(ValueError, match="not eligible"):
        ivf_scan.fused_list_scan_topk(
            *args, k=64, metric_kind=ivf_scan.L2, approx=True,
            interpret=True, extract="binned")
    # at the boundary the model admits (k=13: loss ~4.7% <= 5%) the
    # arm still clears the documented band
    want = _scan_oracle(storage, ids, buckets, qv, 13)
    od, oi = ivf_scan.fused_list_scan_topk(
        *args, k=13, metric_kind=ivf_scan.L2, approx=True,
        interpret=True, extract="binned")
    oi = np.asarray(oi)
    hits = np.mean([
        len(np.intersect1d(oi[b, g], want[b, g])) / 13
        for b in range(oi.shape[0]) for g in range(oi.shape[1])
    ])
    assert hits > 0.93, hits


def test_list_scan_fold_width_and_invalids(rng):
    """fold's output contract: width R*128, invalid slots (+inf, -1)."""
    storage, ids, sizes, buckets, qv = _scan_workload(rng, cap=256)
    sizes = np.full_like(sizes, 100)      # short lists -> invalid tail
    qj = jnp.asarray(qv)
    qaux = jnp.sum(qj * qj, axis=2)
    norms = jnp.asarray((storage ** 2).sum(2))
    od, oi = ivf_scan.fused_list_scan_topk(
        jnp.asarray(storage), jnp.asarray(ids), jnp.asarray(sizes),
        jnp.asarray(buckets), qj, qaux, norms, None,
        k=10, metric_kind=ivf_scan.L2, approx=True, interpret=True,
        extract="fold")
    assert od.shape[2] == 256             # R=2 lane stacks
    od, oi = np.asarray(od), np.asarray(oi)
    assert ((oi == -1) == np.isinf(od)).all()
    # 100 valid rows -> exactly 2*100=200 finite? no: lanes hold at most
    # R entries each; just require every finite id to be a live row
    live = oi[oi >= 0]
    assert (live % 256 < 100).all()


# ---------------------------------------------------------------------------
# hierarchical select_k vs the hardware top_k oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [16, 256, 1000])
def test_hierarchical_select_bitwise_vs_topk(rng, k):
    """DISTINCT values (shuffled iota — exact in f32 below 2^24): with
    no ties the hierarchical rung must agree bitwise with the hardware
    top_k on both values and ids. (Under ties top_k breaks by global
    lowest index while the hierarchical merge is only per-tile stable —
    the all-equal stability contract is pinned in test_select_k.py.)"""
    from raft_tpu.matrix.select_k import _hierarchical_topk, _select_k

    x = np.stack([rng.permutation(9000) for _ in range(8)]).astype(
        np.float32)
    x = jnp.asarray(x)
    for select_min in (True, False):
        hv, hi = _hierarchical_topk(x, k, select_min)
        tv, ti = _select_k(x, k, select_min)
        np.testing.assert_array_equal(np.asarray(hi), np.asarray(ti))
        np.testing.assert_array_equal(np.asarray(hv), np.asarray(tv))
