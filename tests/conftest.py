"""Test configuration: force an 8-virtual-device CPU platform.

The reference tests all require a GPU (SURVEY.md §4); our analog of its
`LocalCUDACluster` multi-GPU-without-a-cluster strategy is JAX's virtual
multi-device CPU host — sharding/collective tests run on an 8-device mesh
with no TPU attached. Must be set before jax is imported anywhere.
"""

import os

# Env-var JAX_PLATFORMS does not override the axon TPU plugin; the config
# update below does. XLA_FLAGS must still be set before backend init.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# --- dead-backend exit guard (VERDICT r5 weak #6) ---------------------------
# Shared implementation: raft_tpu/core/exit_guard.py (also wired into the
# long-running scripts — r5_measure_all / capture_dispatch_tables). Tests
# run on the forced-CPU platform, so the hanging plugin teardown has
# nothing to save: record the real session rc, hard-exit with it from an
# atexit hook registered AFTER `import jax` (LIFO ⇒ guard runs first).
# Disable explicitly with RAFT_TPU_NO_EXIT_GUARD=1.
from raft_tpu.core.exit_guard import install as _install_exit_guard  # noqa: E402
from raft_tpu.core.exit_guard import set_exit_rc as _set_exit_rc  # noqa: E402

_install_exit_guard()


def pytest_sessionfinish(session, exitstatus):
    _set_exit_rc(int(exitstatus))


# Modules dominated by expensive builds (graph construction, kmeans at
# 100k+ rows, process spawning) and name patterns marking heavy
# individual tests. `pytest -m "not slow"` is the minutes-scale subset
# (VERDICT r4 weak #8: the full suite outgrew a 10-minute budget on this
# CPU host); the full suite stays the default.
_SLOW_MODULES = {
    "test_cagra", "test_multihost", "test_bench_run", "test_nn_descent",
    "test_ball_cover",
}
_SLOW_PATTERNS = ("streamed", "cache_only", "sharded_cagra", "raw_residual")


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _SLOW_MODULES or any(p in item.name for p in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(8)
    return Mesh(devs, ("shard",))
