"""Test configuration: force an 8-virtual-device CPU platform.

The reference tests all require a GPU (SURVEY.md §4); our analog of its
`LocalCUDACluster` multi-GPU-without-a-cluster strategy is JAX's virtual
multi-device CPU host — sharding/collective tests run on an 8-device mesh
with no TPU attached. Must be set before jax is imported anywhere.
"""

import os

# Env-var JAX_PLATFORMS does not override the axon TPU plugin; the config
# update below does. XLA_FLAGS must still be set before backend init.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import atexit  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# --- dead-backend exit guard (VERDICT r5 weak #6) ---------------------------
# With the axon TPU plugin installed but the backend unreachable, the
# interpreter HANGS at teardown (the plugin's exit-time client cleanup
# blocks holding the GIL) — a fully green run then sits forever and CI
# reads an external-timeout rc=124 instead of the real pytest rc. Tests
# run on the forced-CPU platform, so that teardown has nothing to save:
# record the real session rc and hard-exit with it from an atexit hook.
# atexit is LIFO and this registration happens AFTER `import jax`, so the
# guard runs BEFORE any backend-client teardown can hang. The guard only
# ARMS when an out-of-tree PJRT plugin could be present (plugin entry
# points / jax_plugins namespace / PJRT env / a non-cpu JAX_PLATFORMS) —
# a plain-CPU machine keeps normal interpreter teardown, so
# earlier-registered atexit hooks (e.g. coverage.py's data save) still
# run there. Disable explicitly with RAFT_TPU_NO_EXIT_GUARD=1.

_SESSION_RC = {"rc": None}


def _pjrt_plugin_possible() -> bool:
    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat and plat.strip().lower() not in ("", "cpu"):
        return True
    if os.environ.get("PJRT_NAMES_AND_LIBRARY_PATHS"):
        return True
    try:
        import importlib.metadata as _md

        if list(_md.entry_points(group="jax_plugins")):
            return True
    except Exception:  # noqa: BLE001
        pass
    try:
        import jax_plugins  # namespace package  # noqa: F401

        return True
    except Exception:  # noqa: BLE001
        return False


def _exit_with_real_rc():
    rc = _SESSION_RC["rc"]
    if rc is None or os.environ.get("RAFT_TPU_NO_EXIT_GUARD"):
        return  # session never finished (collection crash): teardown as-is
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(int(rc))


if _pjrt_plugin_possible():
    atexit.register(_exit_with_real_rc)


def pytest_sessionfinish(session, exitstatus):
    _SESSION_RC["rc"] = int(exitstatus)


# Modules dominated by expensive builds (graph construction, kmeans at
# 100k+ rows, process spawning) and name patterns marking heavy
# individual tests. `pytest -m "not slow"` is the minutes-scale subset
# (VERDICT r4 weak #8: the full suite outgrew a 10-minute budget on this
# CPU host); the full suite stays the default.
_SLOW_MODULES = {
    "test_cagra", "test_multihost", "test_bench_run", "test_nn_descent",
    "test_ball_cover",
}
_SLOW_PATTERNS = ("streamed", "cache_only", "sharded_cagra", "raw_residual")


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _SLOW_MODULES or any(p in item.name for p in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(8)
    return Mesh(devs, ("shard",))
