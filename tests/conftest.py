"""Test configuration: force an 8-virtual-device CPU platform.

The reference tests all require a GPU (SURVEY.md §4); our analog of its
`LocalCUDACluster` multi-GPU-without-a-cluster strategy is JAX's virtual
multi-device CPU host — sharding/collective tests run on an 8-device mesh
with no TPU attached. Must be set before jax is imported anywhere.
"""

import os

# Env-var JAX_PLATFORMS does not override the axon TPU plugin; the config
# update below does. XLA_FLAGS must still be set before backend init.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(8)
    return Mesh(devs, ("shard",))
