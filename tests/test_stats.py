"""Stats tests — oracle = numpy/sklearn-style formulas (reference
cpp/test/stats/*)."""

import numpy as np
import pytest

from raft_tpu import stats


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return rng.standard_normal((200, 8)).astype(np.float32)


def test_moments(data):
    np.testing.assert_allclose(np.asarray(stats.mean(data)), data.mean(0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.stddev(data)), data.std(0),
                               rtol=1e-4, atol=1e-5)
    mu, var = stats.meanvar(data, sample=True)
    np.testing.assert_allclose(np.asarray(var), data.var(0, ddof=1),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.cov(data)),
                               np.cov(data, rowvar=False),
                               rtol=1e-3, atol=1e-4)
    lo, hi = stats.minmax(data)
    np.testing.assert_allclose(np.asarray(lo), data.min(0))
    np.testing.assert_allclose(np.asarray(hi), data.max(0))


def test_weighted_mean(data):
    w = np.abs(np.random.default_rng(1).standard_normal(200)).astype(np.float32)
    want = (data * w[:, None]).sum(0) / w.sum()
    np.testing.assert_allclose(np.asarray(stats.weighted_mean(data, w)), want,
                               rtol=1e-4, atol=1e-5)


def test_histogram():
    x = np.array([0.0, 0.1, 0.5, 0.9, 1.0], np.float32)[:, None]
    counts, edges = stats.histogram(x, 2, lo=0.0, hi=1.0)
    assert counts.sum() == 5
    # matches np.histogram: 0.5 lands in the upper bin, 1.0 clips into it
    np.testing.assert_array_equal(np.asarray(counts)[:, 0], [2, 3])


def test_accuracy_r2():
    assert float(stats.accuracy([1, 2, 3, 4], [1, 2, 0, 4])) == 0.75
    y = np.array([1.0, 2.0, 3.0, 4.0])
    assert abs(float(stats.r2_score(y, y)) - 1.0) < 1e-6
    m = stats.regression_metrics([1.0, 2.0], [1.5, 2.5])
    assert abs(float(m["mean_abs_error"]) - 0.5) < 1e-6


def test_rand_indices():
    a = np.array([0, 0, 1, 1, 2, 2])
    assert abs(float(stats.adjusted_rand_index(a, a)) - 1.0) < 1e-5
    assert abs(float(stats.rand_index(a, a)) - 1.0) < 1e-5
    # permuted labels are still a perfect clustering
    b = np.array([2, 2, 0, 0, 1, 1])
    assert abs(float(stats.adjusted_rand_index(a, b)) - 1.0) < 1e-5


def test_ari_vs_sklearn_formula():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 4, 100)
    b = rng.integers(0, 3, 100)
    try:
        from sklearn.metrics import adjusted_rand_score
        want = adjusted_rand_score(a, b)
        got = float(stats.adjusted_rand_index(a, b))
        assert abs(got - want) < 1e-4
    except ImportError:
        pytest.skip("sklearn unavailable")


def test_entropy_mutual_info():
    a = np.array([0, 0, 1, 1])
    assert abs(float(stats.entropy(a)) - np.log(2)) < 1e-5
    # identical labelings: MI == entropy
    assert abs(float(stats.mutual_info_score(a, a)) - np.log(2)) < 1e-5
    assert abs(float(stats.v_measure(a, a)) - 1.0) < 1e-5
    assert abs(float(stats.homogeneity_score(a, a)) - 1.0) < 1e-5


def test_silhouette():
    # two tight, well-separated blobs -> silhouette near 1
    rng = np.random.default_rng(0)
    a = rng.standard_normal((50, 4)) * 0.01
    b = rng.standard_normal((50, 4)) * 0.01 + 10.0
    x = np.concatenate([a, b]).astype(np.float32)
    labels = np.array([0] * 50 + [1] * 50)
    s = float(stats.silhouette_score(x, labels))
    assert s > 0.95
    try:
        from sklearn.metrics import silhouette_score as sk
        assert abs(s - sk(x, labels)) < 1e-2
    except ImportError:
        pass


def test_information_criterion():
    aic = float(stats.information_criterion(-10.0, 3, 100, "aic"))
    assert abs(aic - 26.0) < 1e-6
    bic = float(stats.information_criterion(-10.0, 3, 100, "bic"))
    assert abs(bic - (20 + 3 * np.log(100))) < 1e-5


def test_neighborhood_recall():
    idx = np.array([[0, 1, 2], [3, 4, 5]])
    ref = np.array([[0, 1, 9], [3, 4, 5]])
    r = float(stats.neighborhood_recall(idx, ref))
    assert abs(r - 5 / 6) < 1e-6
    # distance ties rescue the miss
    d = np.array([[0.0, 1.0, 2.0], [0.0, 1.0, 2.0]])
    rd = np.array([[0.0, 1.0, 2.0], [0.0, 1.0, 2.0]])
    r2 = float(stats.neighborhood_recall(idx, ref, d, rd))
    assert abs(r2 - 1.0) < 1e-6


def test_trustworthiness():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((60, 5)).astype(np.float32)
    # identity embedding is perfectly trustworthy
    t = float(stats.trustworthiness_score(x, x.copy(), n_neighbors=5))
    assert abs(t - 1.0) < 1e-5
    # random embedding is much worse
    y = rng.standard_normal((60, 2)).astype(np.float32)
    t2 = float(stats.trustworthiness_score(x, y, n_neighbors=5))
    assert t2 < t
    try:
        from sklearn.manifold import trustworthiness as sk_t
        want = sk_t(x, y, n_neighbors=5)
        assert abs(t2 - want) < 5e-2
    except ImportError:
        pass


def test_silhouette_empty_class_id():
    # regression: a class id with zero members must not poison b(i)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((30, 4)) * 0.01
    b = rng.standard_normal((30, 4)) * 0.01 + 10.0
    x = np.concatenate([a, b]).astype(np.float32)
    labels = np.array([0] * 30 + [2] * 30)  # class 1 empty
    s = float(stats.silhouette_score(x, labels))
    assert s > 0.95
