"""obs_report tooling smoke (ISSUE 13; marker ``obs``, rides tier-1).

Renders waterfalls, the per-stage table, a federated exposition, and
the cross-process stitch view from the COMMITTED fixture dump
``tests/data/flight_r13_fixture.jsonl`` (a real LocalGroup fabric run
in flight mode: one clean full-coverage search + one hedged race under
``slow@proc``), so the reporting path cannot rot without tier-1
noticing — the committed-fixture smoke the ISSUE's CI satellite asks
for."""

import importlib.util
import json
import os
import sys

import pytest

pytestmark = pytest.mark.obs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(ROOT, "tests", "data",
                       "flight_r13_fixture.jsonl")


@pytest.fixture(scope="module")
def obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(ROOT, "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def events(obs_report):
    return obs_report.load_events(FIXTURE)


def test_fixture_holds_waterfalls_and_snapshot(obs_report, events):
    wfs = obs_report.waterfalls_from_events(events)
    assert len(wfs) == 2
    assert all(w["entry"] == "fabric.search" and w["status"] == "ok"
               for w in wfs)
    # the second search ran under slow@proc:0 — its hedge race is in
    # the record, winner marked
    statuses = [s["status"] for w in wfs for s in w["stages"]]
    assert "hedge_win" in statuses and "hedge_loser" in statuses
    assert any(e["kind"] == "snapshot" for e in events)


def test_render_waterfall_ascii(obs_report, events):
    wf = obs_report.waterfalls_from_events(events)[-1]
    text = obs_report.render_waterfall(wf)
    assert wf["trace_id"] in text
    for stage in ("rpc", "worker_scan", "merge"):
        assert stage in text
    assert "*hedge-win*" in text and "(hedge loser)" in text
    assert "#" in text                     # bars actually rendered


def test_waterfall_cli_smoke(obs_report, capsys):
    rc = obs_report.main(["waterfall", FIXTURE])
    out = capsys.readouterr().out
    assert rc == 0
    assert "per-stage attribution" in out
    assert "worker_scan" in out and "merge" in out


def test_waterfall_cli_trace_filter_and_summary(obs_report, events,
                                                capsys):
    tid = obs_report.waterfalls_from_events(events)[0]["trace_id"]
    rc = obs_report.main(["waterfall", FIXTURE, "--trace", tid,
                          "--summary"])
    out = capsys.readouterr().out
    assert rc == 0 and "1 waterfall(s)" in out
    rc = obs_report.main(["waterfall", FIXTURE, "--trace", "no-such"])
    assert rc == 1


def test_federate_cli_merges_under_source_labels(obs_report, tmp_path,
                                                 capsys):
    fed_json = str(tmp_path / "fed.json")
    # the fixture twice under two labels = two "processes" federated
    other = str(tmp_path / "worker1.jsonl")
    with open(FIXTURE) as src, open(other, "w") as dst:
        dst.write(src.read())
    rc = obs_report.main(["federate", FIXTURE, other,
                          "--json", fed_json])
    out = capsys.readouterr().out
    assert rc == 0
    assert "# TYPE raft_tpu_" in out
    fed = json.load(open(fed_json))
    assert fed["mode"] == "federated" and len(fed["workers"]) == 2
    labels = {p["labels"]["worker"]
              for m in fed["metrics"].values() if isinstance(m, dict)
              for p in m.get("points", [])}
    assert labels == set(fed["workers"])


def test_stitch_groups_by_trace_id(obs_report, events, capsys):
    rc = obs_report.main(["stitch", FIXTURE])
    out = capsys.readouterr().out
    assert rc == 0
    wf_ids = {w["trace_id"]
              for w in obs_report.waterfalls_from_events(events)}
    for tid in wf_ids:
        assert f"trace {tid}" in out
    # worker-side spans stitched under the same id as the waterfall
    assert "span:" in out and "waterfall:" in out


# ---------------------------------------------------------------------------
# recall timeline (graft-gauge, ISSUE 19)
# ---------------------------------------------------------------------------


def _recall_metric_lines(index, rung, triplets, t0=100.0):
    """Flight ``kind="metric"`` lines as the monitor writes them:
    estimate, ci_low, ci_high per update, in that order."""
    lines = []
    for i, (est, lo, hi) in enumerate(triplets):
        t = t0 + i
        for name, v in (("serve.recall_estimate", est),
                        ("serve.recall_ci_low", lo),
                        ("serve.recall_ci_high", hi)):
            lines.append(json.dumps({
                "t": t, "kind": "metric", "name": name, "value": v,
                "labels": {"index": index, "rung": rung}}))
    return lines


def test_recall_points_pair_gauge_triplets(obs_report, tmp_path):
    dump = tmp_path / "flight-q.jsonl"
    dump.write_text("\n".join(
        _recall_metric_lines("t", "all",
                             [(0.9, 0.8, 0.96), (0.95, 0.9, 0.98)])
        + _recall_metric_lines("t", "16", [(0.7, 0.6, 0.8)])
        + [json.dumps({"t": 1.0, "kind": "metric",
                       "name": "serve.queue_depth", "value": 3.0,
                       "labels": {"index": "t"}})]) + "\n")
    pts = obs_report.recall_points([str(dump)])
    assert len(pts) == 3
    by_rung = {}
    for p in pts:
        by_rung.setdefault(p["rung"], []).append(p)
    assert len(by_rung["all"]) == 2 and len(by_rung["16"]) == 1
    first = by_rung["all"][0]
    assert (first["estimate"], first["ci_low"], first["ci_high"]) \
        == (0.9, 0.8, 0.96)
    # timeline ordering within the series
    assert by_rung["all"][0]["t"] < by_rung["all"][1]["t"]


def test_recall_cli_band_flags_proven_breach(obs_report, tmp_path,
                                             capsys):
    dump = tmp_path / "flight-q.jsonl"
    dump.write_text("\n".join(_recall_metric_lines(
        "t", "all", [(0.95, 0.9, 0.99), (0.7, 0.6, 0.8)])) + "\n")
    out_json = str(tmp_path / "pts.json")
    rc = obs_report.main(["recall", str(dump), "--band", "0.9",
                          "--json", out_json])
    out = capsys.readouterr().out
    assert rc == 0
    assert "band=0.90" in out
    # the ci_high=0.8 point is a PROVEN breach; ci_high=0.99 is not
    assert out.count("ALARM") == 1
    assert "[" in out and "]" in out and "*" in out
    dumped = json.load(open(out_json))
    assert len(dumped["points"]) == 2


def test_recall_snapshot_sidecar_and_federated_workers(obs_report,
                                                       tmp_path,
                                                       capsys):
    snap = {"time_unix": 50.0, "metrics": {
        "serve.recall_estimate": {"kind": "gauge", "points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.97},
            {"labels": {"worker": "w1", "index": "t", "rung": "all"},
             "value": 0.91}]},
        "serve.recall_ci_low": {"kind": "gauge", "points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.93},
            {"labels": {"worker": "w1", "index": "t", "rung": "all"},
             "value": 0.85}]},
        "serve.recall_ci_high": {"kind": "gauge", "points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.99},
            {"labels": {"worker": "w1", "index": "t", "rung": "all"},
             "value": 0.95}]}}}
    path = tmp_path / "fed.obs.json"
    path.write_text(json.dumps(snap))
    pts = obs_report.recall_points([str(path)])
    # a federated sidecar's worker label wins over the filename
    assert {p["worker"] for p in pts} == {"w0", "w1"}
    rc = obs_report.main(["recall", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "worker=w0" in out and "worker=w1" in out


def test_recall_cli_no_points_is_rc1(obs_report, tmp_path, capsys):
    rc = obs_report.main(["recall", FIXTURE])
    capsys.readouterr()
    assert rc == 1


def test_obs_report_runs_as_script():
    """The CLI entry the r5 battery / a chip-day operator shells out
    to: a subprocess run over the fixture exits 0 and prints bars."""
    import subprocess

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "obs_report.py"),
         "waterfall", "--summary", FIXTURE],
        capture_output=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr.decode()
    assert b"per-stage attribution" in r.stdout
