"""Multi-host serving fabric (ISSUE 6; marker ``multihost``).

Covers: exact routing vs a surviving-shard oracle (bitwise — the oracle
runs the SAME per-shard search + merge code path), hedged retries past
an injected slow worker, circuit breaking + half-open re-admission
after a worker death, the two-phase cluster hot-swap (commit AND
abort-rollback legs), the cross-process SIGKILL kill-and-resume drill,
and the chaos acceptance: a closed-loop load run under injected
``dead@proc`` + ``slow@proc`` faults with a mid-run swap, where every
answer must be bitwise-correct for the shards it reports covered.

Most tests run the in-process :class:`LocalGroup` transport (identical
router semantics, no spawn cost); the kill-and-resume and chaos tests
spawn real ``multiprocessing`` workers. Worker counts and timeouts are
bounded so the suite rides tier-1.
"""

import threading
import time

import numpy as np
import pytest

from raft_tpu import serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.comms import procgroup
from raft_tpu.resilience import ShardDropoutError, faultinject
from raft_tpu.serve import fabric as fabmod

pytestmark = [pytest.mark.multihost, pytest.mark.threadsan]


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # ISSUE 7: the fabric suite runs with SANITIZED locks (router,
    # health breakers, worker groups) — every run doubles as the
    # zero-inversion / zero-hold-budget-breach acceptance
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    faultinject.clear()
    tuning.reload()
    yield
    faultinject.clear()
    tuning.reload()


def _params(**kw):
    base = dict(
        n_workers=3, replication=2, rpc_deadline_s=3.0,
        rpc_retries=2, retry_backoff_s=0.01, hedge_after_ms=25.0,
        halfopen_after_s=0.05, probe_timeout_s=10.0,
        swap_deadline_s=30.0, slow_ms=150.0, auto_probe=False,
        fail_threshold=2,
    )
    base.update(kw)
    return serve.FabricParams(**base)


def _data(n=96, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, dim)).astype(np.float32),
            rng.standard_normal((4, dim)).astype(np.float32))


def _oracle(dataset, q, k, n_workers, covered, algo="brute_force"):
    """Surviving-shard oracle: the same shard search + merge code path
    the workers and router run, restricted to ``covered`` shards —
    bitwise identity is the contract, not approximate recall."""
    bounds = fabmod.shard_bounds(dataset.shape[0], n_workers)
    results = {}
    for s in range(n_workers):
        if s not in covered:
            results[s] = None
            continue
        entry = procgroup.build_shard_entry(
            dataset[bounds[s]:bounds[s + 1]], bounds[s], algo)
        d, i = procgroup.search_shard_entry(entry, q, k)
        results[s] = (0, d, i)
    return fabmod.merge_shard_results(n_workers, results, q.shape[0], k)


# ---------------------------------------------------------------------------
# LocalGroup: routing, hedging, circuit breaking, swap protocol
# ---------------------------------------------------------------------------


def test_fabric_matches_oracle_full_coverage():
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        d, i, cov = fab.search(q, 5)
        assert cov.shape == (4,) and (cov == 1.0).all()
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        # single-row convenience: 1-D query promotes to [1, dim]
        d1, i1, cov1 = fab.search(q[0], 5)
        np.testing.assert_array_equal(i1, oi[:1])
        assert cov1.shape == (1,)


def test_fabric_hedges_past_slow_worker():
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        fab.search(q, 5)                      # warm every traced shape
        before = fab.stats()["counters"].get("hedges", 0)
        # shard 0's primary owner is worker 0: stall exactly that RPC
        # past the 25ms hedge threshold; the replica (worker 1) covers
        with faultinject.inject("slow@proc:0*1"):
            d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        assert fab.stats()["counters"].get("hedges", 0) > before


def test_fabric_dead_worker_degrades_with_honest_coverage():
    ds, q = _data()
    p = _params(replication=1, rpc_deadline_s=0.5)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        with faultinject.inject("dead@proc:1"):
            d, i, cov = fab.search(q, 5)
        # shard 1 lost; per-row coverage says so on every row
        np.testing.assert_allclose(cov, 2 / 3)
        od, oi, validity = _oracle(ds, q, 5, 3, covered={0, 2})
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        assert not validity[1].any()
        # the confirmed-dead worker's circuit opened
        assert fab.stats()["health"][1] == "open"
        assert fab.stats()["counters"]["dropouts"] >= 1
        # partial_ok=False refuses silent degradation
        with pytest.raises(ShardDropoutError):
            fab.search(q, 5, partial_ok=False)
        # coverage floor: 2/3 < 0.9 floor refuses too
        fab.params.coverage_floor = 0.9
        with pytest.raises(ShardDropoutError):
            fab.search(q, 5)


def test_fabric_halfopen_readmission_after_restart():
    ds, q = _data()
    p = _params(replication=1, rpc_deadline_s=0.5)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        with faultinject.inject("dead@proc:1"):
            fab.search(q, 5)
        assert fab.stats()["health"][1] == "open"
        fab.restart_worker(1)                 # fresh worker, no state
        assert fab.stats()["health"][1] == "open"   # not routed yet
        # half-open probe: ping ok but stale -> resync -> closed
        deadline = time.monotonic() + 20.0
        while fab.stats()["health"][1] != "closed":
            fab.probe_now()
            assert time.monotonic() < deadline, fab.stats()
            time.sleep(0.05)
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)
        c = fab.stats()["counters"]
        assert c.get("restarts", 0) == 1 and c.get("probes", 0) >= 1


def test_fabric_two_phase_swap_commits_everywhere():
    ds, q = _data()
    rng = np.random.default_rng(7)
    ds2 = rng.standard_normal((120, 8)).astype(np.float32)
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        assert fab.generation() == 1
        gen = fab.swap(ds2)
        assert gen == 2 and fab.generation() == 2
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle(ds2, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        # the retired generation is garbage-collected on every worker
        # once its last router pin drained (retire is async)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            gens = [fab.group.call(r, "ping", {}).result(timeout=5.0)
                    ["gens"] for r in range(3)]
            if all(g == [2] for g in gens):
                break
            time.sleep(0.05)
        assert all(g == [2] for g in gens), gens


def test_fabric_swap_abort_rolls_back_cleanly():
    ds, q = _data()
    rng = np.random.default_rng(8)
    ds2 = rng.standard_normal((120, 8)).astype(np.float32)
    p = _params(swap_deadline_s=1.0)
    with serve.Fabric(ds, params=p, group="local") as fab:
        # one worker's prepare response vanishes -> barrier aborts,
        # every worker rolls back, generation 1 keeps serving
        with faultinject.inject("drop@rpc:prepare"):
            with pytest.raises(serve.FabricSwapError):
                fab.swap(ds2)
        assert fab.generation() == 1
        assert fab.stats()["counters"]["swap_aborts"] == 1
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)       # still OLD content
        # nothing staged anywhere; the next swap succeeds
        assert fab.swap(ds2) == 3
        d, i, _ = fab.search(q, 5)
        od, oi, _ = _oracle(ds2, q, 5, 3, covered={0, 1, 2})
        np.testing.assert_array_equal(i, oi)


def test_fabric_ivf_flat_workers_match_oracle():
    ds, q = _data(n=120)
    p = _params(worker_algo="ivf_flat")
    with serve.Fabric(ds, params=p, group="local") as fab:
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 1, 2},
                            algo="ivf_flat")
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)


def test_fabric_dropped_rpc_does_not_leak_pending():
    """A response that never arrives (drop@rpc) must not pin its Future
    + query payload in the transport's pending map forever: the router
    forgets abandoned requests at the deadline / on hedge win."""
    ds, q = _data()
    p = _params(replication=1, rpc_deadline_s=0.3, rpc_retries=1)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        with faultinject.inject("drop@rpc:search*2"):
            d, i, cov = fab.search(q, 5)
        assert cov.min() < 1.0          # some shard lost its response
        # every abandoned request was forgotten at the transport
        deadline = time.monotonic() + 5.0
        while any(w.pending for w in fab.group._workers):
            assert time.monotonic() < deadline, [
                dict(w.pending) for w in fab.group._workers]
            time.sleep(0.02)


# ---------------------------------------------------------------------------
# graft-trace: waterfall assembly under faults (ISSUE 13)
# ---------------------------------------------------------------------------


@pytest.fixture()
def _obs_on():
    from raft_tpu import obs

    obs.set_mode("on")
    obs.reset()
    yield obs
    obs.reset()
    obs.set_mode(None)


def test_fabric_trace_complete_waterfall_full_coverage(_obs_on):
    obs = _obs_on
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        fab.search(q, 5)                      # warm (compile noise)
        obs.trace.reset()
        fab.search(q, 5)
        (wf,) = obs.trace_report()
        assert wf["entry"] == "fabric.search" and wf["status"] == "ok"
        assert wf["attrs"]["coverage_min"] == 1.0
        assert wf["attrs"]["covered_shards"] == [0, 1, 2]
        # one ok rpc + one device-complete worker_scan per shard, then
        # the merge closes the waterfall
        for s in range(3):
            assert any(st["stage"] == "rpc" and st["shard"] == s
                       and st["status"] == "ok"
                       for st in wf["stages"])
            assert any(st["stage"] == "worker_scan" and st["shard"] == s
                       and st["device_complete"]
                       for st in wf["stages"])
        assert wf["stages"][-1]["stage"] == "merge"
        # every stage is time-positioned inside the trace
        assert all("t_off_ms" in st for st in wf["stages"]
                   if st.get("ms") is not None)


def test_fabric_trace_partial_waterfall_carries_failure(_obs_on):
    """dead@proc mid-query (no replica): the waterfall completes as
    DEGRADED, carrying the failed rpc attempt for the lost shard while
    the surviving shards' spans are intact — the partial-visibility
    contract."""
    obs = _obs_on
    ds, q = _data()
    p = _params(replication=1, rpc_deadline_s=0.5)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        obs.trace.reset()
        with faultinject.inject("dead@proc:1"):
            d, i, cov = fab.search(q, 5)
        np.testing.assert_allclose(cov, 2 / 3)
        (wf,) = obs.trace_report()
        assert wf["status"] == "degraded"
        assert wf["attrs"]["covered_shards"] == [0, 2]
        fails = [st for st in wf["stages"]
                 if st.get("shard") == 1 and st["stage"] == "rpc"]
        assert fails and all(
            st["status"] in ("failed", "timeout") for st in fails)
        assert any(st.get("kind") == "dead_backend" for st in fails)
        # the survivors' scans still ride the same waterfall
        assert {st["shard"] for st in wf["stages"]
                if st["stage"] == "worker_scan"} == {0, 2}


def test_fabric_trace_failover_replica_spans_in_waterfall(_obs_on):
    """dead@proc with a replica: the dead primary's failed attempt AND
    the failover replica's rpc + worker_scan land in ONE waterfall, and
    the answer stays fully covered."""
    obs = _obs_on
    ds, q = _data()
    p = _params(rpc_deadline_s=2.0)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        obs.trace.reset()
        with faultinject.inject("dead@proc:0"):
            d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        (wf,) = obs.trace_report()
        assert wf["status"] == "ok"
        # shard 0's primary owner is worker 0 (died); its replica
        # (worker 1) answered — both attempts visible
        s0 = [st for st in wf["stages"] if st.get("shard") == 0]
        assert any(st["stage"] == "rpc" and st["worker"] == 0
                   and st["status"] in ("failed", "timeout")
                   for st in s0)
        assert any(st["stage"] == "rpc" and st["worker"] == 1
                   and st["status"] == "ok" for st in s0)
        assert any(st["stage"] == "worker_scan" and st["worker"] == 1
                   for st in s0)


def test_fabric_trace_hedged_race_records_both_attempts(_obs_on):
    """A hedged race records BOTH attempts as sibling rpc stages with
    the winner marked hedge_win and the loser hedge_loser."""
    obs = _obs_on
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        fab.search(q, 5)
        obs.trace.reset()
        with faultinject.inject("slow@proc:0*1"):
            d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        (wf,) = obs.trace_report()
        s0 = [st for st in wf["stages"]
              if st.get("shard") == 0 and st["stage"] == "rpc"]
        assert {st["status"] for st in s0} == {"hedge_win",
                                               "hedge_loser"}
        win = next(st for st in s0 if st["status"] == "hedge_win")
        lose = next(st for st in s0 if st["status"] == "hedge_loser")
        assert win["worker"] == 1 and lose["worker"] == 0
        # the hedge fired AFTER the primary (time-positioned later)
        assert win["t_off_ms"] > lose["t_off_ms"]


def test_fabric_trace_raised_query_finishes_failed(_obs_on):
    """A coverage shortfall that RAISES to the caller must complete its
    waterfall as failed, not degraded/ok — the answered/complete
    columns and the chaos >=99% bar count only queries the caller
    actually got an answer for."""
    obs = _obs_on
    ds, q = _data()
    p = _params(replication=1, rpc_deadline_s=0.5)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.search(q, 5)
        obs.trace.reset()
        with faultinject.inject("dead@proc:1"):
            with pytest.raises(ShardDropoutError):
                fab.search(q, 5, partial_ok=False)
        (wf,) = obs.trace_report()
        assert wf["status"] == "failed"
        assert wf["attrs"]["error"] == "ShardDropoutError"
        assert not obs.trace.waterfall_complete(wf)
        # same contract for the coverage floor under partial_ok=True
        fab.params.coverage_floor = 0.9
        obs.trace.reset()
        with faultinject.inject("dead@proc:1"):
            with pytest.raises(ShardDropoutError):
                fab.search(q, 5)
        assert obs.trace_report()[-1]["status"] == "failed"


def test_fabric_trace_ambient_context_linked_not_adopted(_obs_on):
    """An enclosing ambient context must not be adopted as the search's
    own waterfall id (cross-process ids have no local record; a local
    one would be stolen from the caller) — the entry mints its own and
    links the parent."""
    obs = _obs_on
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        fab.search(q, 5)
        obs.trace.reset()
        outer = obs.start_trace("caller.op")
        with obs.trace.activate(outer):
            fab.search(q, 5)
        (wf,) = obs.trace_report()             # the fabric's record
        assert wf["trace_id"] != outer.trace_id
        assert wf["attrs"]["parent_trace"] == outer.trace_id
        # the caller's own record is untouched and still completable
        done = obs.trace.finish(outer)
        assert done is not None and done["status"] == "ok"


def test_fabric_trace_rpc_payload_carries_context(_obs_on):
    """The propagation contract GL019 enforces, observed live: the
    search RPC payload crossing the transport carries the minted
    (trace_id, parent_span_id) field."""
    obs = _obs_on
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        seen = []
        orig = fab.group.call

        def spy(rank, method, payload=None):
            if method == "search":
                seen.append(payload.get(obs.trace.WIRE_FIELD))
            return orig(rank, method, payload)

        fab.group.call = spy
        fab.search(q, 5)
        assert seen and all(
            w and set(w) == {"trace_id", "parent_span_id"}
            for w in seen)
        tid = {w["trace_id"] for w in seen}
        assert len(tid) == 1                  # one id names the query
        assert obs.trace_report()[-1]["trace_id"] == tid.pop()


def test_fabric_federation_local_group_shared_registry_not_duplicated(
        _obs_on):
    """LocalGroup workers share the ROUTER's registry: they answer the
    scrape (listed in ``workers``) but hand back NO metrics — the
    shared series arrive once, under worker="router", instead of
    (n_workers+1)x-ing every fleet sum."""
    obs = _obs_on
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        fab.search(q, 5)
        fed = fab.collect_metrics()
        assert fed["mode"] == "federated"
        assert fed["workers"] == ["w0", "w1", "w2"]   # all answered
        assert fed["shared_registry"] is True
        assert fed["generation"] == 1
        assert fed["worker_health"] == {"w0": "closed", "w1": "closed",
                                        "w2": "closed"}
        # every series appears exactly ONCE, as the router's
        labels = {p["labels"]["worker"]
                  for m in fed["metrics"].values()
                  for p in m.get("points", ())}
        assert labels == {"router"}
        pts = fed["metrics"]["fabric.worker_rpcs_total"]["points"]
        assert len([p for p in pts
                    if p["labels"].get("method") == "search"]) == 1
        # and the whole thing renders as one valid exposition
        text = fab.export_federated_prometheus()
        assert "raft_tpu_fabric_worker_rpcs_total_total" not in text
        assert 'raft_tpu_fabric_worker_rpcs_total{' in text


# ---------------------------------------------------------------------------
# real multiprocessing: SIGKILL kill-and-resume + chaos acceptance
# ---------------------------------------------------------------------------


def test_fabric_kill_and_resume_multiprocess():
    """ISSUE 6 satellite: kill a worker mid-stream (SIGKILL), assert
    partial answers carry correct coverage, the circuit opens, and a
    restarted worker is re-admitted through half-open probing with
    bitwise-identical results vs an uninjected run on the surviving
    shards."""
    ds, q = _data(n=60)
    p = _params(replication=1, rpc_deadline_s=10.0, probe_timeout_s=10.0,
                hedge_after_ms=5000.0)
    fab = serve.Fabric(ds, params=p, group="proc")
    try:
        d0, i0, cov0 = fab.search(q, 5)
        assert (cov0 == 1.0).all()
        fab.group.kill(1)                     # SIGKILL, mid-stream
        d, i, cov = fab.search(q, 5)
        np.testing.assert_allclose(cov, 2 / 3)
        od, oi, _ = _oracle(ds, q, 5, 3, covered={0, 2})
        np.testing.assert_array_equal(i, oi)  # bitwise vs the oracle
        np.testing.assert_array_equal(d, od)
        assert fab.stats()["health"][1] == "open"
        # rejoin: respawn + half-open probing until the circuit closes
        fab.restart_worker(1)
        deadline = time.monotonic() + 60.0
        while fab.stats()["health"][1] != "closed":
            fab.probe_now()
            assert time.monotonic() < deadline, fab.stats()
            time.sleep(0.25)
        d2, i2, cov2 = fab.search(q, 5)
        assert (cov2 == 1.0).all()
        np.testing.assert_array_equal(i2, i0)
        np.testing.assert_array_equal(d2, d0)
    finally:
        fab.close()


def test_fabric_chaos_acceptance_multiprocess():
    """ISSUE 6 acceptance: closed-loop load under injected dead@proc +
    slow@proc faults with a mid-run cluster hot-swap. The fabric must
    return ZERO wrong answers (every answer bitwise-correct for the
    shards it reports covered, per its pinned generation), report
    coverage honestly, never mix generations, complete (or fully roll
    back) the swap, and re-admit the killed worker through half-open
    probing — with counters matching the injected fault script."""
    from raft_tpu import obs

    rng = np.random.default_rng(3)
    ds1 = rng.standard_normal((120, 8)).astype(np.float32)
    ds2 = rng.standard_normal((150, 8)).astype(np.float32)
    datasets = {}
    p = _params(replication=2, rpc_deadline_s=5.0, slow_ms=300.0,
                hedge_after_ms=25.0, probe_timeout_s=10.0,
                swap_deadline_s=60.0)
    obs.set_mode("on")
    obs.reset()        # earlier tests' waterfalls must not ride along
    fab = serve.Fabric(ds1, params=p, group="proc",
                       fault_spec="dead@proc:2,slow@proc:1*2")
    datasets[1] = ds1
    recorded = []
    rec_lock = threading.Lock()
    stop = threading.Event()

    def client(wid):
        crng = np.random.default_rng(100 + wid)
        while not stop.is_set():
            q = crng.standard_normal((1, 8)).astype(np.float32)
            out = fab.search(q, 4, detail=True)
            with rec_lock:
                recorded.append((q,) + out)

    threads = [threading.Thread(target=client, args=(w,), daemon=True)
               for w in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(1.5)                       # faults fire under load
        gen2 = fab.swap(ds2)                  # barrier inside the storm
        assert gen2 == 2
        datasets[2] = ds2
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # worker 2 died on its first search; rejoin through half-open
        assert fab.stats()["health"][2] == "open"
        fab.restart_worker(2)
        deadline = time.monotonic() + 60.0
        while fab.stats()["health"][2] != "closed":
            fab.probe_now()
            assert time.monotonic() < deadline, fab.stats()
            time.sleep(0.25)
        dF, iF, covF = fab.search(rng.standard_normal(
            (2, 8)).astype(np.float32), 4)
        assert (covF == 1.0).all()
        counters = fab.stats()["counters"]
        health = fab.stats()["health"]
        # federation over REAL worker processes (each owns its own
        # registry): every live worker answers and its series arrive
        # under its own label — the per-worker half LocalGroup's
        # shared-registry twin cannot exercise
        fed = fab.collect_metrics()
        assert fed["workers"] == ["w0", "w1", "w2"]
        assert "shared_registry" not in fed
        pts = fed["metrics"]["fabric.worker_rpcs_total"]["points"]
        per_worker = {p["labels"]["worker"] for p in pts
                      if p["labels"].get("method") == "search"}
        assert {"w0", "w1", "w2"} <= per_worker
    finally:
        fab.close()
        obs.set_mode(None)

    # --- zero wrong answers: bitwise vs the surviving-shard oracle ----
    assert len(recorded) >= 10
    degraded = 0
    for q, d, i, cov, validity, gen_id in recorded:
        assert gen_id in datasets, gen_id     # no phantom generations
        # coverage must restate the validity matrix exactly (honesty)
        np.testing.assert_allclose(cov, validity.mean(axis=0))
        rows_uniform = [validity[s].all() or not validity[s].any()
                        for s in range(3)]
        assert all(rows_uniform)              # no NaN rows in this drill
        covered = {s for s in range(3) if validity[s].all()}
        if len(covered) < 3:
            degraded += 1
        od, oi, _ = _oracle(datasets[gen_id], q, 4, 3, covered=covered)
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
    # --- counters match the injected fault script ---------------------
    # slow@proc:1*2 stalled two responses 300ms past the 25ms hedge
    # threshold -> hedges fired; dead@proc:2 killed a worker -> its
    # circuit cycled open -> half_open -> closed on rejoin
    assert counters.get("hedges", 0) >= 1
    assert counters.get("restarts", 0) == 1
    assert counters.get("mixed_gen", 0) == 0  # swap atomicity held
    assert counters.get("swaps", 0) == 2      # initial load + mid-run
    assert counters.get("swap_aborts", 0) == 0
    assert health == {0: "closed", 1: "closed", 2: "closed"}
    # --- graft-trace acceptance (ISSUE 13): under the same chaos, the
    # trace layer assembled a COMPLETE end-to-end waterfall for >=99%
    # of answered queries: every shard the answer reports covered
    # contributed a device-complete worker_scan stage from the worker
    # that actually served it, and a merge stage closed the record
    from raft_tpu.obs.trace import waterfall_complete

    wfs = [w for w in obs.trace_report()
           if w["entry"] == "fabric.search"
           and w["status"] in ("ok", "degraded")]
    assert len(wfs) >= len(recorded)          # one per answered query
    complete = sum(1 for w in wfs if waterfall_complete(w))
    assert complete / len(wfs) >= 0.99, (complete, len(wfs))
    # hedge attempts recorded as sibling stages with a marked winner
    all_stages = [s for w in wfs for s in w["stages"]]
    assert any(s["status"] == "hedge_win" for s in all_stages)
    # the dead worker's mid-query failures are visible as failed/timeout
    # rpc attempts inside otherwise-complete waterfalls
    assert any(s["stage"] == "rpc"
               and s["status"] in ("failed", "timeout")
               for s in all_stages)


# ---------------------------------------------------------------------------
# graft-race regressions (ISSUE 7): the call-vs-kill lost-future race
# ---------------------------------------------------------------------------


class _KillingCounter:
    """Deterministic interleave seam: ``call()`` draws its request id
    from this counter BETWEEN its aliveness decision and the future's
    registration in the old code — firing ``kill()`` here reproduces
    exactly the window where the drain ran before the registration and
    the future was never resolved (its caller hung to the deadline)."""

    def __init__(self, group, rank):
        self.group = group
        self.rank = rank
        self.fired = False
        self.n = 1000

    def __iter__(self):
        return self

    def __next__(self):
        if not self.fired:
            self.fired = True
            self.group.kill(self.rank)
        self.n += 1
        return self.n


def test_localgroup_call_racing_kill_never_hangs_future():
    """Register-or-reject must be atomic against the kill drain: a
    future created by a call that razor-raced kill() is either rejected
    immediately or drained by _fail_pending — never left forever
    pending (the pre-fix behavior, which hung the router to its RPC
    deadline)."""
    group = procgroup.LocalGroup(2)
    try:
        group._req_ids = _KillingCounter(group, 0)
        fut = group.call(0, "ping", {})
        # resolved IMMEDIATELY — no waiting on a worker that will never
        # answer
        assert fut.done()
        with pytest.raises(Exception, match="not alive|killed"):
            fut.result(timeout=0)
        # the untouched worker still answers
        assert group.call(1, "ping", {}).result(timeout=10)["rank"] == 1
    finally:
        group.close()


def test_procgroup_fail_pending_blocks_later_calls():
    """The _ProcWorker.dead_reason seam: once a worker's futures were
    drained, a racing call() must see the verdict under the same lock
    and fail fast instead of registering into the void. (LocalGroup's
    spawn-free twin exercises the same contract above; here we pin the
    parent-side bookkeeping without paying a process spawn.)"""
    from concurrent.futures import Future

    w = procgroup._ProcWorker(0, None, None, None)
    assert w.dead_reason is None
    f1: Future = Future()
    with w.lock:
        w.pending[1] = f1
    # the drain marks the worker dead and fails everything registered
    pg = procgroup.ProcGroup.__new__(procgroup.ProcGroup)
    pg._fail_pending(w, "worker 0 killed")
    assert w.dead_reason == "worker 0 killed"
    assert f1.done() and f1.exception() is not None
    assert w.pending == {}


def test_fabric_threadsan_suite_verdict_zzz():
    """Suite-level ISSUE-7 acceptance (runs last in file order): the
    fabric tier's observed lock order stayed acyclic under sanitized
    locks, with zero hold-budget breaches."""
    s = lockwatch.stats()
    assert s["inversions"] == 0 and s["budget_breaches"] == 0, s
