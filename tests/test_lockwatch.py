"""graft-race engine 2 (dynamic) tests: the RAFT_TPU_THREADSAN lock
sanitizer (ISSUE 7).

Covers: the planted lock-order inversion (raises with the cycle path
named — the ISSUE acceptance), hold-time budget breaches, RLock
reentrancy (no self-edge, outermost-hold timing), Condition integration
over both wrapper kinds, cross-thread release (the compacting-flag
handoff shape, both raw and through make_flag_lock), the graph export
that feeds ``graft-lint --reconcile``, the off-mode plain-primitive
fast path, and the failure dump through graft-scope."""

import threading
import time

import pytest

from raft_tpu import obs
from raft_tpu.analysis import lockwatch

pytestmark = pytest.mark.threadsan


@pytest.fixture(autouse=True)
def _sanitized(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    monkeypatch.delenv(lockwatch.BUDGET_ENV_VAR, raising=False)
    lockwatch.reset()
    yield
    lockwatch.reset()


def test_off_mode_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv(lockwatch.ENV_VAR, raising=False)
    assert not isinstance(lockwatch.make_lock("x"), lockwatch.SanLock)
    assert not isinstance(lockwatch.make_rlock("x"), lockwatch.SanRLock)


def test_planted_inversion_raises_with_cycle_path():
    """The ISSUE acceptance: an observed order inversion raises, and
    the error names the full cycle path."""
    a = lockwatch.make_lock("hier.A")
    b = lockwatch.make_lock("hier.B")
    with a:
        with b:
            pass
    with pytest.raises(lockwatch.LockOrderInversion) as ei:
        with b:
            with a:
                pass
    assert ei.value.cycle == ["hier.A", "hier.B", "hier.A"]
    assert "hier.A -> hier.B -> hier.A" in str(ei.value)
    assert lockwatch.stats()["inversions"] == 1
    # the failing acquisition was unwound: both locks acquirable again
    with a:
        with b:
            pass


def test_three_lock_cycle_detected():
    a = lockwatch.make_lock("tri.A")
    b = lockwatch.make_lock("tri.B")
    c = lockwatch.make_lock("tri.C")
    with a, b:
        pass
    with b, c:
        pass
    with pytest.raises(lockwatch.LockOrderInversion) as ei:
        with c, a:
            pass
    assert ei.value.cycle[0] == ei.value.cycle[-1]
    assert set(ei.value.cycle) == {"tri.A", "tri.B", "tri.C"}


def test_same_name_distinct_instances_flagged():
    """Two same-named locks nested (two MutableStates) have no
    intra-class tiebreak: AB/BA-prone, flagged immediately."""
    a1 = lockwatch.make_lock("same.X")
    a2 = lockwatch.make_lock("same.X")
    with pytest.raises(lockwatch.LockOrderInversion):
        with a1:
            with a2:
                pass


def test_consistent_order_is_silent():
    a = lockwatch.make_lock("ok.A")
    b = lockwatch.make_lock("ok.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockwatch.stats()["inversions"] == 0
    assert lockwatch.order_graph()["ok.A"].keys() == {"ok.B"}


def test_rlock_reentrancy_no_self_edge():
    r = lockwatch.make_rlock("re.R")
    with r:
        with r:
            with r:
                pass
    assert lockwatch.stats()["inversions"] == 0
    # one logical acquisition recorded, not three
    assert lockwatch.stats()["acquires"] == 1


def test_hold_budget_breach_raises(monkeypatch):
    monkeypatch.setenv(lockwatch.BUDGET_ENV_VAR, "10")
    lk = lockwatch.make_lock("budget.L")
    with pytest.raises(lockwatch.HoldBudgetExceeded) as ei:
        with lk:
            time.sleep(0.05)
    assert ei.value.lock_name == "budget.L"
    assert ei.value.held_ms > 10
    assert lockwatch.stats()["budget_breaches"] == 1
    # the lock itself was released before the raise
    assert lk.acquire(blocking=False)
    lk.release()


def test_rlock_budget_spans_outermost_hold(monkeypatch):
    monkeypatch.setenv(lockwatch.BUDGET_ENV_VAR, "10")
    r = lockwatch.make_rlock("budget.R")
    with pytest.raises(lockwatch.HoldBudgetExceeded):
        with r:
            with r:        # inner release must NOT end the hold window
                pass
            time.sleep(0.05)


def test_condition_over_sanitized_lock_roundtrip():
    lk = lockwatch.make_lock("cond.L")
    cond = lockwatch.make_condition(lk)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=5)
    assert hits == [1]
    assert lockwatch.stats()["inversions"] == 0


def test_condition_over_sanitized_rlock_roundtrip():
    r = lockwatch.make_rlock("cond.R")
    cond = threading.Condition(r)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=5)
    assert hits == [1]


def test_cross_thread_release_clears_acquirer_held_set():
    """The compacting-flag handoff: thread A try-acquires, thread B
    releases. A's held-set must not keep a phantom entry that turns
    A's next acquisition into a false inversion."""
    flag = lockwatch.SanLock("handoff.flag")
    other = lockwatch.make_lock("handoff.other")
    assert flag.acquire(blocking=False)

    t = threading.Thread(target=flag.release, daemon=True)
    t.start()
    t.join(timeout=5)

    # were the phantom still held, this would record handoff.flag ->
    # handoff.other and a later reverse nesting would invert; more
    # directly, the held-set must be empty now:
    with other:
        pass
    g = lockwatch.order_graph()
    assert "handoff.flag" not in g


def test_flag_lock_is_exempt():
    """make_flag_lock returns a plain Lock even when sanitizing: a
    try-acquire-only handoff flag cannot deadlock."""
    flag = lockwatch.make_flag_lock("serve.compacting")
    assert isinstance(flag, type(threading.Lock()))


def test_flag_lock_cross_thread_handoff():
    """The real compact() shape end-to-end: the caller try-acquires the
    flag, a worker thread does the work and releases it from a DIFFERENT
    thread, all while sanitized locks are in play. The flag must stay
    out of the order graph (it is a plain Lock), leave no phantom entry
    in either thread's held-set, and be immediately re-acquirable."""
    flag = lockwatch.make_flag_lock("serve.compacting")
    state = lockwatch.make_rlock("serve.mutation")
    done = threading.Event()

    assert flag.acquire(blocking=False)      # single-flight claim
    assert not flag.acquire(blocking=False)  # second entrant bounces

    def worker():
        with state:                          # sanitized work under flag
            pass
        flag.release()                       # handoff release, thread B
        done.set()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert done.wait(timeout=5)
    t.join(timeout=5)

    # released cross-thread: next single-flight round starts clean
    assert flag.acquire(blocking=False)
    flag.release()
    g = lockwatch.order_graph()
    assert "serve.compacting" not in g
    assert not any("serve.compacting" in succs for succs in g.values())
    assert lockwatch.stats()["inversions"] == 0


def test_export_graph_writes_reconcile_artifact(tmp_path):
    """export_graph dumps the observed graph in the shape
    --reconcile consumes, and merge=True unions a prior artifact
    (sharded runs accumulate instead of clobbering)."""
    import json

    a = lockwatch.make_lock("exp.A")
    b = lockwatch.make_lock("exp.B")
    with a:
        with b:
            pass
    target = str(tmp_path / "graph.json")
    assert lockwatch.export_graph(target) == target
    doc = json.load(open(target))
    assert "exp.B" in doc["graph"]["exp.A"]
    assert doc["stats"]["acquires"] == 2

    # second process observed a different edge: merge keeps both
    lockwatch.reset()
    c = lockwatch.make_lock("exp.C")
    with b:
        with c:
            pass
    lockwatch.export_graph(target, merge=True)
    doc = json.load(open(target))
    assert "exp.B" in doc["graph"]["exp.A"]
    assert "exp.C" in doc["graph"]["exp.B"]


def test_export_graph_env_var_default(monkeypatch, tmp_path):
    target = str(tmp_path / "env_graph.json")
    monkeypatch.setenv(lockwatch.EXPORT_ENV_VAR, target)
    lk = lockwatch.make_lock("envexp.A")
    with lk:
        pass
    assert lockwatch.export_graph() == target
    with pytest.raises(ValueError):
        monkeypatch.delenv(lockwatch.EXPORT_ENV_VAR)
        lockwatch.export_graph()


def test_failure_dump_reaches_obs(monkeypatch, tmp_path):
    """On inversion the acquisition graph rides through graft-scope:
    lockwatch.failures counter, the lockwatch_failure breadcrumb WITH
    the graph attached, and (in flight mode) an automatic ring dump.
    The breadcrumb content is asserted explicitly — an exception inside
    the best-effort dump path is swallowed by design, so only a
    content check proves the plumbing actually ran."""
    import json

    monkeypatch.setenv(obs.DIR_VAR, str(tmp_path))
    obs.set_mode("flight")
    try:
        obs.reset()
        a = lockwatch.make_lock("dump.A")
        b = lockwatch.make_lock("dump.B")
        with a:
            with b:
                pass
        with pytest.raises(lockwatch.LockOrderInversion):
            with b:
                with a:
                    pass
        snap = obs.snapshot(runtime_gauges=False)
        pts = snap["metrics"]["lockwatch.failures"]["points"]
        assert any(p["labels"].get("kind") == "inversion" for p in pts)
        dump = obs.last_dump_path()
        assert dump is not None, "flight mode must auto-dump the ring"
        lines = [json.loads(line) for line in open(dump)]
        evt = [e for e in lines if e.get("event") == "lockwatch_failure"]
        assert evt, lines
        assert evt[0]["failure"] == "inversion"
        assert evt[0]["cycle"] == "dump.A -> dump.B -> dump.A"
        assert "dump.A" in evt[0]["order_graph"]
    finally:
        obs.reset()
        obs.set_mode("off")
