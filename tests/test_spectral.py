"""Spectral partition / modularity tests — scipy.sparse.linalg + known
community structure oracles (mirrors cpp/test/ spectral_matrix / cluster
solvers tests)."""

import numpy as np
import pytest
import scipy.sparse as sps
import scipy.sparse.linalg as spla
import scipy.sparse.csgraph as csgraph
from sklearn.metrics import adjusted_rand_score

from raft_tpu import spectral, sparse


def _two_block_graph(n_per=30, p_in=0.5, p_out=0.01, seed=0):
    rng = np.random.default_rng(seed)
    n = 2 * n_per
    a = np.zeros((n, n), np.float32)
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n_per) == (j < n_per)
            p = p_in if same else p_out
            if rng.uniform() < p:
                a[i, j] = a[j, i] = 1.0
    # guarantee connectivity
    a[0, n_per] = a[n_per, 0] = 1.0
    for i in range(n - 1):
        if a[i].sum() == 0:
            a[i, i + 1] = a[i + 1, i] = 1.0
    return a


def test_embedding_matches_scipy_eigsh():
    a = _two_block_graph()
    adj = sparse.dense_to_csr(a)
    evals, evecs = spectral.fit_embedding(adj, 3, n_iters=60)
    lap = csgraph.laplacian(sps.csr_matrix(a.astype(np.float64)))
    want = np.sort(spla.eigsh(lap, k=4, which="SM")[0])[1:4]
    np.testing.assert_allclose(np.asarray(evals), want, rtol=1e-2, atol=1e-3)


def test_partition_two_communities():
    a = _two_block_graph()
    adj = sparse.dense_to_csr(a)
    labels, evals, evecs = spectral.partition(adj, 2)
    truth = np.array([0] * 30 + [1] * 30)
    assert adjusted_rand_score(truth, np.asarray(labels)) > 0.95


def test_modularity_maximization():
    a = _two_block_graph(p_in=0.6, p_out=0.02, seed=1)
    adj = sparse.dense_to_csr(a)
    labels, evals, evecs = spectral.modularity_maximization(adj, 2)
    truth = np.array([0] * 30 + [1] * 30)
    assert adjusted_rand_score(truth, np.asarray(labels)) > 0.9
    q = spectral.analyze_modularity(adj, labels)
    # ground-truth communities on a strong 2-block graph: Q near 0.4-0.5
    assert float(q) > 0.3


def test_analyze_partition():
    a = _two_block_graph(seed=2)
    adj = sparse.dense_to_csr(a)
    truth = np.array([0] * 30 + [1] * 30, np.int32)
    edge_cut, cost = spectral.analyze_partition(adj, truth)
    # cross edges are the p_out ones (+ the forced bridge)
    cross = a[:30, 30:].sum()
    np.testing.assert_allclose(float(edge_cut), cross, rtol=1e-5)
    # a garbage partition must cut more
    bad = np.arange(60) % 2
    bad_cut, _ = spectral.analyze_partition(adj, bad.astype(np.int32))
    assert float(bad_cut) > float(edge_cut)


def test_modularity_oracle():
    """analyze_modularity against the textbook formula computed by hand
    in numpy on a two-community graph: Q(true partition) matches the
    dense-matrix oracle and beats both a random and the trivial
    one-cluster partition (Q=0)."""
    a = _two_block_graph()
    adj = sparse.dense_to_csr(a)
    n = a.shape[0]
    true_labels = (np.arange(n) >= n // 2).astype(np.int32)

    # dense oracle: Q = (1/2m) sum_ij [A_ij - d_i d_j / 2m] delta(c_i,c_j)
    d = a.sum(1)
    two_m = a.sum()
    B = a - np.outer(d, d) / two_m
    same = true_labels[:, None] == true_labels[None, :]
    q_want = (B * same).sum() / two_m

    q_got = float(spectral.analyze_modularity(adj, true_labels))
    np.testing.assert_allclose(q_got, q_want, rtol=1e-5, atol=1e-6)
    assert q_got > 0.3                                  # strong communities
    # degenerate single cluster has Q == 0 by definition
    q_one = float(spectral.analyze_modularity(adj, np.zeros(n, np.int32)))
    np.testing.assert_allclose(q_one, 0.0, atol=1e-6)
    rng = np.random.default_rng(0)
    q_rand = float(spectral.analyze_modularity(
        adj, rng.integers(0, 2, n).astype(np.int32)))
    assert q_got > q_rand + 0.2


def test_modularity_maximization_recovers_communities():
    """modularity_maximization's own partition scores near the planted
    one on the oracle metric."""
    a = _two_block_graph()
    adj = sparse.dense_to_csr(a)
    n = a.shape[0]
    true_labels = (np.arange(n) >= n // 2).astype(np.int32)
    labels, _, _ = spectral.modularity_maximization(adj, 2)
    q_true = float(spectral.analyze_modularity(adj, true_labels))
    q_got = float(spectral.analyze_modularity(adj, np.asarray(labels)))
    assert q_got > q_true - 0.05, (q_got, q_true)
