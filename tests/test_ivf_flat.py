"""IVF-Flat tests — reference pattern (cpp/test/neighbors/ann_ivf_flat.cuh):
oracle = naive KNN, assertion = recall >= n_probes/n_lists-derived bound;
plus build-structure, extend, filter and serialization round-trips."""

import numpy as np
import pytest

from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_flat
from tests.oracles import eval_recall, naive_knn


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    centers = rng.uniform(-5, 5, (32, 24)).astype(np.float32)
    x = (centers[rng.integers(0, 32, 8000)]
         + 0.8 * rng.standard_normal((8000, 24))).astype(np.float32)
    q = (centers[rng.integers(0, 32, 200)]
         + 0.8 * rng.standard_normal((200, 24))).astype(np.float32)
    return x, q


def _build(x, n_lists=32, metric="sqeuclidean", **kw):
    params = ivf_flat.IndexParams(n_lists=n_lists, metric=metric,
                                  kmeans_n_iters=10, **kw)
    return ivf_flat.build(params, x)


def test_build_structure(dataset):
    x, _ = dataset
    index = _build(x)
    assert index.n_lists == 32
    assert index.size == x.shape[0]
    sizes = np.asarray(index.list_sizes)
    assert sizes.sum() == x.shape[0]
    assert sizes.min() > 0
    # every row lands in exactly one list with its own id
    _, ids = ivf_flat.reconstruct_dataset(index)
    assert sorted(ids.tolist()) == list(range(x.shape[0]))
    # stored vectors must match the source rows
    vecs, ids0 = ivf_flat.get_list_data(index, 0)
    np.testing.assert_array_equal(vecs, x[ids0])


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "inner_product"])
def test_search_recall_high_probes(dataset, metric):
    x, q = dataset
    k = 10
    index = _build(x, metric=metric)
    # probing every list == exact search
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64, bucket_batch=4,
                               compute_dtype="f32", local_recall_target=1.0)
    dist, idx = ivf_flat.search(sp, index, q, k)
    _, want = naive_knn(q, x, k, metric)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_search_recall_partial_probes(dataset):
    x, q = dataset
    k = 10
    index = _build(x)
    sp = ivf_flat.SearchParams(n_probes=8, query_group=64, bucket_batch=4)
    _, idx = ivf_flat.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    # reference bound: recall >= ~n_probes/n_lists-derived; clustered data
    # with 8/32 probes lands well above 0.8
    assert eval_recall(np.asarray(idx), want) > 0.8


def test_search_distances_match_oracle(dataset):
    x, q = dataset
    k = 5
    index = _build(x)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    dist, idx = ivf_flat.search(sp, index, q, k)
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.sort(d2, axis=1)[:, :k]
    np.testing.assert_allclose(np.asarray(dist), want, rtol=1e-3, atol=1e-2)


def test_extend(dataset):
    x, q = dataset
    k = 10
    index = _build(x[:4000])
    assert index.size == 4000
    index = ivf_flat.extend(index, x[4000:])
    assert index.size == 8000
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    _, idx = ivf_flat.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_prefilter(dataset):
    x, q = dataset
    k = 10
    n = x.shape[0]
    index = _build(x)
    allowed = np.zeros(n, bool)
    allowed[: n // 4] = True
    bits = Bitset.from_dense(allowed)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    _, idx = ivf_flat.search(sp, index, q, k, prefilter=bits)
    idx = np.asarray(idx)
    assert (idx < n // 4).all() or ((idx == -1) | (idx < n // 4)).all()
    _, want = naive_knn(q, x[: n // 4], k)
    assert eval_recall(idx, want) > 0.99


def test_extend_then_prefilter(dataset):
    """extend × prefilter (ISSUE 5 satellite): a filter built BEFORE the
    extend still applies afterwards — default "drop" rejects the new
    rows, out_of_range="keep" admits them (tombstone semantics)."""
    from raft_tpu.neighbors.common import BitsetFilter

    x, q = dataset
    k = 10
    n_old = 4000
    index = _build(x[:n_old])
    allowed = np.zeros(n_old, bool)
    allowed[: n_old // 2] = True
    bits = Bitset.from_dense(allowed)          # narrower than the index
    index = ivf_flat.extend(index, x[n_old:])  # ids n_old..8000 appended
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)

    # default drop: only kept OLD rows can surface
    _, idx = ivf_flat.search(sp, index, q, k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | ((idx < n_old // 2))).all()
    _, want = naive_knn(q, x[: n_old // 2], k)
    assert eval_recall(idx, want) > 0.99

    # keep: new rows join the allowed set
    _, idx2 = ivf_flat.search(
        sp, index, q, k, prefilter=BitsetFilter(bits, out_of_range="keep"))
    idx2 = np.asarray(idx2)
    assert ((idx2 < n_old // 2) | (idx2 >= n_old)).all()
    sub = np.concatenate([np.arange(n_old // 2),
                          np.arange(n_old, x.shape[0])])
    _, want_sub = naive_knn(q, x[sub], k)
    assert eval_recall(idx2, sub[want_sub]) > 0.99


def test_prefilter_fewer_than_k_valid(dataset):
    """Restrictive filter (< k allowed points): ids at sentinel distance
    must be -1, never a filtered-out id (ADVICE r1 medium finding)."""
    x, q = dataset
    k = 10
    n = x.shape[0]
    index = _build(x)
    allowed = np.zeros(n, bool)
    allowed[:3] = True  # only 3 points pass the filter
    bits = Bitset.from_dense(allowed)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    _, idx = ivf_flat.search(sp, index, q[:50], k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < 3)).all()
    # each query finds exactly the 3 allowed points + 7 sentinels
    assert (np.sort(idx, axis=1)[:, -3:] >= 0).all()
    assert (idx == -1).sum(axis=1).min() == k - 3


def test_cosine_partial_probe_recall():
    """Cosine metric: coarse partition and probe must share the angular
    geometry (ADVICE r1 medium finding) — partial probing keeps recall."""
    rng = np.random.default_rng(3)
    # unnormalized data with magnitude spread: L2 partitions would diverge
    # badly from cosine probes here
    dirs = rng.standard_normal((16, 24)).astype(np.float32)
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    picks = rng.integers(0, 16, 6000)
    scale = rng.uniform(0.5, 20.0, (6000, 1)).astype(np.float32)
    x = (scale * (dirs[picks] + 0.15 * rng.standard_normal((6000, 24)))
         ).astype(np.float32)
    q = (dirs[rng.integers(0, 16, 150)]
         + 0.15 * rng.standard_normal((150, 24))).astype(np.float32)
    index = _build(x, n_lists=16, metric="cosine")
    sp = ivf_flat.SearchParams(n_probes=4, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    _, idx = ivf_flat.search(sp, index, q, 10)
    _, want = naive_knn(q, x, 10, "cosine")
    assert eval_recall(np.asarray(idx), want) > 0.9


def test_small_k_exceeding_list(dataset):
    x, q = dataset
    index = _build(x, n_lists=32)
    cap = index.storage.shape[1]
    # k bigger than any single list but within n_probes * cap
    k = min(2 * cap, 512)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0)
    _, idx = ivf_flat.search(sp, index, q[:20], k)
    _, want = naive_knn(q[:20], x, k)
    assert eval_recall(np.asarray(idx), want) > 0.99


@pytest.mark.parametrize("metric", ["sqeuclidean", "inner_product", "cosine"])
def test_pallas_scan_interpret_matches_xla(dataset, metric):
    """The fused Pallas list-scan kernel (interpret mode on CPU) must agree
    with the XLA bucketized scan."""
    x, q = dataset
    k = 10
    index = _build(x, metric=metric)
    kw = dict(n_probes=8, query_group=64, bucket_batch=4,
              compute_dtype="f32", local_recall_target=1.0)
    d_x, i_x = ivf_flat.search(
        ivf_flat.SearchParams(scan_impl="xla", **kw), index, q[:50], k)
    d_p, i_p = ivf_flat.search(
        ivf_flat.SearchParams(scan_impl="pallas_interpret", **kw),
        index, q[:50], k)
    agree = np.mean(np.asarray(i_x) == np.asarray(i_p))
    assert agree > 0.95  # ties may reorder; ids must essentially match
    np.testing.assert_allclose(
        np.asarray(d_x), np.asarray(d_p), rtol=2e-2, atol=2e-2
    )


def test_pallas_scan_interpret_filter(dataset):
    """Filter fused into the Pallas kernel keeps the bitset contract."""
    x, q = dataset
    k, n = 10, dataset[0].shape[0]
    index = _build(x)
    allowed = np.zeros(n, bool)
    allowed[: n // 4] = True
    bits = Bitset.from_dense(allowed)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64,
                               compute_dtype="f32", local_recall_target=1.0,
                               scan_impl="pallas_interpret")
    _, idx = ivf_flat.search(sp, index, q[:50], k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < n // 4)).all()
    _, want = naive_knn(q[:50], x[: n // 4], k)
    assert eval_recall(idx, want) > 0.99


def test_serialize_roundtrip(dataset, tmp_path):
    x, q = dataset
    index = _build(x)
    p = str(tmp_path / "ivf.idx")
    ivf_flat.save(p, index)
    loaded = ivf_flat.load(p)
    sp = ivf_flat.SearchParams(n_probes=8, query_group=64)
    d1, i1 = ivf_flat.search(sp, index, q, 10)
    d2, i2 = ivf_flat.search(sp, loaded, q, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def test_build_without_data_then_extend(dataset):
    x, q = dataset
    params = ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10,
                                  add_data_on_build=False)
    index = ivf_flat.build(params, x)
    assert index.size == 0
    with pytest.raises(ValueError):
        ivf_flat.search(ivf_flat.SearchParams(n_probes=4), index, q, 5)
    index = ivf_flat.extend(index, x)
    assert index.size == x.shape[0]
    _, idx = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, query_group=64,
                              compute_dtype="f32", local_recall_target=1.0),
        index, q, 10)
    _, want = naive_knn(q, x, 10)
    assert eval_recall(np.asarray(idx), want) > 0.99


def test_search_fast_defaults(dataset):
    # default fast path: bf16 matmuls + approx per-list top-k — still high
    # recall when probing everything
    x, q = dataset
    k = 10
    index = _build(x)
    sp = ivf_flat.SearchParams(n_probes=32, query_group=64, bucket_batch=4)
    _, idx = ivf_flat.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(idx), want) > 0.9


def test_pallas_binned_short_list_ids(dataset):
    """Regression: the binned (approx) extraction must emit real ids even
    when the winner sits at list column 0 and the list is shorter than
    cap (untouched bins share binpos=0 and must not leak their -1 id)."""
    x, q = dataset
    k = 10
    index = _build(x, n_lists=64)  # short, uneven lists vs padded cap
    sp = ivf_flat.SearchParams(n_probes=16, query_group=64, bucket_batch=4,
                               compute_dtype="f32",
                               local_recall_target=0.95,  # approx path
                               scan_impl="pallas_interpret")
    d, i = ivf_flat.search(sp, index, q[:50], k)
    d, i = np.asarray(d), np.asarray(i)
    assert not ((i == -1) & np.isfinite(d)).any()
    assert (i >= 0).all()  # plenty of candidates here — no -1 expected


def test_pallas_large_k_deep_binned(dataset):
    """64 < k <= 256 on the fused approx path uses the R-deep lane
    binning; its per-list loss is ~C(k,R+1)/128^R, so ids must still
    near-match the exact XLA scan."""
    x, q = dataset
    k = 100
    index = _build(x)
    kw = dict(n_probes=16, query_group=64, bucket_batch=4,
              compute_dtype="f32")
    _, i_x = ivf_flat.search(
        ivf_flat.SearchParams(scan_impl="xla", local_recall_target=1.0,
                              **kw), index, q[:30], k)
    _, i_p = ivf_flat.search(
        ivf_flat.SearchParams(scan_impl="pallas_interpret",
                              local_recall_target=0.95, **kw),
        index, q[:30], k)
    i_x, i_p = np.asarray(i_x), np.asarray(i_p)
    overlap = np.mean([
        len(set(i_x[r]) & set(i_p[r])) / k for r in range(i_x.shape[0])
    ])
    assert overlap > 0.9, overlap


def test_bf16_storage_recall(dataset):
    """storage_dtype='bf16' halves scan bytes at near-identical recall
    (the fused kernel is HBM-bound; reference's fp16 instantiation
    analog)."""
    import jax.numpy as jnp

    x, q = dataset
    k = 10
    p32 = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10)
    pbf = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10,
                               storage_dtype="bf16")
    i32 = ivf_flat.build(p32, x)
    ibf = ivf_flat.build(pbf, x)
    assert ibf.storage.dtype == jnp.bfloat16
    assert i32.storage.dtype == jnp.float32
    sp = ivf_flat.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, idx32 = ivf_flat.search(sp, i32, q, k)
    _, idxbf = ivf_flat.search(sp, ibf, q, k)
    _, want = naive_knn(q, x, k)
    r32 = eval_recall(np.asarray(idx32), want)
    rbf = eval_recall(np.asarray(idxbf), want)
    assert rbf > r32 - 0.02, (rbf, r32)


def test_bf16_storage_serialize_roundtrip(dataset, tmp_path):
    """bf16 storage survives the .npy container round trip (ml_dtypes
    bfloat16 is not a stock numpy dtype — regression guard)."""
    import jax.numpy as jnp

    x, q = dataset
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, storage_dtype="bf16",
                             kmeans_n_iters=5), x)
    p = str(tmp_path / "bf16.idx")
    ivf_flat.save(p, idx)
    loaded = ivf_flat.load(p)
    assert loaded.storage.dtype == jnp.bfloat16
    sp = ivf_flat.SearchParams(n_probes=16, query_group=64, bucket_batch=4)
    _, i1 = ivf_flat.search(sp, idx, q[:32], 5)
    _, i2 = ivf_flat.search(sp, loaded, q[:32], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
