"""graft-lint engine 1 (AST) tests: per-rule positive/negative fixtures,
suppression machinery, CLI exit codes, and the tier-1 gate over the
shipped tree (zero unsuppressed findings — the JAX-port analog of the
reference's RAFT_EXPLICIT_INSTANTIATE_ONLY build gate)."""

import json
import os
import sys
import textwrap

import pytest

from raft_tpu.analysis.cli import main as cli_main
from raft_tpu.analysis.kernels import lint_paths as kern_lint_paths
from raft_tpu.analysis.kernels import lint_source as kern_lint_source
from raft_tpu.analysis.lint import (
    documented_metric_names,
    lint_file,
    lint_paths,
    lint_source,
)
from raft_tpu.analysis.races import lint_paths as race_lint_paths
from raft_tpu.analysis.races import lint_source as race_lint_source
from raft_tpu.analysis.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "raft_tpu")


def _rules(src, only=None):
    findings = lint_source(textwrap.dedent(src), "fixture.py")
    open_f = [f for f in findings if not f.suppressed]
    if only:
        open_f = [f for f in open_f if f.rule == only]
    return [f.rule for f in open_f], open_f


def _kern_rules(src, only=None):
    findings = kern_lint_source(textwrap.dedent(src), "fixture.py")
    open_f = [f for f in findings if not f.suppressed]
    if only:
        open_f = [f for f in open_f if f.rule == only]
    return [f.rule for f in open_f], open_f


# ---------------------------------------------------------------------------
# GL001 host-sync
# ---------------------------------------------------------------------------


def test_gl001_item_in_jit_positive():
    rules, _ = _rules("""
        import jax, jax.numpy as jnp

        @jax.jit
        def hot(x):
            return x + x.max().item()
    """)
    assert "GL001" in rules


def test_gl001_float_of_jnp_positive():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            return float(jnp.max(jnp.abs(x)))
    """)
    assert rules == ["GL001"]


def test_gl001_np_asarray_in_scan_body_positive():
    rules, _ = _rules("""
        import jax, numpy as np

        def outer(xs):
            def step(carry, x):
                return carry + np.asarray(x), None
            return jax.lax.scan(step, 0.0, xs)
    """)
    assert "GL001" in rules


def test_gl001_traced_param_float_positive():
    rules, _ = _rules("""
        import jax, functools

        @functools.partial(jax.jit, static_argnums=(1,))
        def hot(x, k):
            return float(x) + k
    """)
    assert "GL001" in rules


def test_gl001_static_arg_and_host_code_negative():
    rules, _ = _rules("""
        import jax, functools, numpy as np

        @functools.partial(jax.jit, static_argnums=(1,))
        def hot(x, k):
            return x * int(k)          # static arg: fine

        def host(meta):
            return float(meta["arg"]) + int(3)   # no device values

        def build(rows):
            return np.asarray(rows)    # numpy-on-numpy: fine
    """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL002 tracer-branch
# ---------------------------------------------------------------------------


def test_gl002_branch_on_jnp_positive():
    rules, _ = _rules("""
        import jax, jax.numpy as jnp

        @jax.jit
        def hot(x):
            if jnp.any(x > 0):
                return x
            return -x
    """)
    assert "GL002" in rules


def test_gl002_while_on_traced_param_positive():
    rules, _ = _rules("""
        import jax

        @jax.jit
        def hot(n):
            while n > 0:
                n = n - 1
            return n
    """)
    assert "GL002" in rules


def test_gl002_negatives():
    rules, _ = _rules("""
        import jax, jax.numpy as jnp

        @jax.jit
        def hot(x, norms=None):
            if norms is None:                 # structural: fine
                norms = jnp.sum(x * x, 1)
            if x.dtype == jnp.bfloat16:       # metadata: fine
                x = x.astype(jnp.float32)
            if jnp.issubdtype(x.dtype, jnp.floating):   # metadata call
                x = x + 1
            return x, norms

        def host(x):
            if jnp.any(x > 0):                # outside traced scope: fine
                return 1
            return 0
    """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL003 int->float ordering
# ---------------------------------------------------------------------------


def test_gl003_astype_into_topk_positive():
    rules, _ = _rules("""
        import jax, jax.numpy as jnp

        def select(n, k):
            ids = jnp.arange(n)
            keys = ids.astype(jnp.float32)      # >2^24 collapse
            return jax.lax.top_k(-keys, k)
    """)
    assert "GL003" in rules


def test_gl003_direct_nesting_positive():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def worst(indices):
            return jnp.argsort(indices.astype(jnp.float32))
    """)
    assert "GL003" in rules


def test_gl003_negatives():
    rules, _ = _rules("""
        import jax, jax.numpy as jnp

        def fine(dists, k):
            return jax.lax.top_k(-dists.astype(jnp.float32), k)  # floats in

        def also_fine(ids):
            return ids.astype(jnp.float32) * 2.0   # no ordering consumer
    """, only="GL003")
    assert rules == []


# ---------------------------------------------------------------------------
# GL004 f64
# ---------------------------------------------------------------------------


def test_gl004_positive_and_string_dtype():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            return x.astype(jnp.float64)

        def g(x):
            return x.astype("float64")
    """)
    assert rules.count("GL004") == 2


def test_gl004_negative():
    rules, _ = _rules("""
        import numpy as np

        def f(x):
            return x.astype(np.float32)
    """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL005 undated-perf
# ---------------------------------------------------------------------------


def test_gl005_undated_comment_positive():
    rules, _ = _rules("""
        # the fused path is ~3x faster than the scattered one
        X = 1
    """)
    assert rules == ["GL005"]


def test_gl005_undated_docstring_qps_positive():
    rules, _ = _rules('''
        def search():
            """Runs at 195 QPS on SIFT-1M."""
    ''')
    assert rules == ["GL005"]


def test_gl005_dated_negatives():
    rules, _ = _rules('''
        # the fused path is ~3x faster (r3, v5e) than the scattered one
        def search():
            """14.7k QPS on SIFT-1M (BENCH_r02.json)."""

        def qualitative():
            """dramatically faster than a full sort for k << c"""
    ''')
    assert rules == []


# ---------------------------------------------------------------------------
# GL006 blockspec — the kern engine's literal FALLBACK screen (computed
# accounting for resolved pallas_call sites is tested further below and
# in test_kernel_contracts.py)
# ---------------------------------------------------------------------------


def test_gl006_off_tile_positive():
    rules, _ = _kern_rules("""
        from jax.experimental import pallas as pl

        def kernel_specs():
            return [pl.BlockSpec((16, 100), lambda i: (i, 0)),
                    pl.BlockSpec((12, 256), lambda i: (i, 0))]
    """)
    assert rules.count("GL006") == 2   # 100 % 128, 12 % 8


def test_gl006_vmem_budget_positive():
    rules, _ = _kern_rules("""
        from jax.experimental import pallas as pl

        def huge():
            return pl.BlockSpec((8192, 1024), lambda i: (i, 0))
    """)
    assert "GL006" in rules            # 32 MiB > 16 MiB budget


def test_gl006_negatives():
    rules, _ = _kern_rules("""
        from jax.experimental import pallas as pl

        def ok(cap, g):
            return [pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    pl.BlockSpec((1, 1, cap), lambda i: (i, 0, 0)),
                    pl.BlockSpec((g, 256), lambda i: (i, 0))]
    """)
    assert rules == []


def test_gl006_retired_from_ast_engine():
    """The literal screen no longer runs in the AST engine — GL006 is
    the kern engine's jurisdiction (computed accounting + fallback)."""
    rules, _ = _rules("""
        from jax.experimental import pallas as pl

        def kernel_specs():
            return pl.BlockSpec((16, 100), lambda i: (i, 0))
    """)
    assert rules == []


def test_gl006_computed_vmem_over_budget():
    """The tentpole: VMEM accounting through COMPUTED shapes — the
    block size flows in from a caller, through arithmetic the literal
    heuristic never saw."""
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x, rows):
            big = rows * 1024
            return pl.pallas_call(
                kern,
                grid=(1,),
                in_specs=[pl.BlockSpec((big, 1024), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((big, 1024), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((big, 1024), jnp.float32),
            )(jnp.zeros((big, 1024), jnp.float32))

        def caller(x):
            return run(x, 8)
    """)
    assert rules == ["GL006"]
    assert "witness" in fs[0].message


def test_gl006_unevaluated_literal_spec_still_screened():
    """Review fix (r6): a resolved site exempts only the spec nodes it
    actually evaluated — a literal off-lane spec the interpreter never
    reached (here: inside a loop with an unknowable condition) in the
    SAME function must still hit the literal fallback screen."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x, flags):
            extras = []
            while flags.pop():
                extras.append(pl.BlockSpec((16, 100), lambda i: (i, 0)))
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
            )(jnp.zeros((256, 128), jnp.float32))
    """)
    assert "GL006" in rules      # the (16, 100) literal, off-lane


def test_gl006_resolved_site_not_double_flagged_by_literal_screen():
    """A site the evaluator resolves gets computed checks only — the
    literal screen must not re-flag its in-budget literal specs."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
            )(jnp.zeros((256, 128), jnp.float32))
    """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL015 kernel-oob (kern engine: index-map bounds + tail masks)
# ---------------------------------------------------------------------------


_OOB_SEED = """
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def run(x):
    return pl.pallas_call(
        kern,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i + 1, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((512, 128), jnp.float32),
    )(jnp.zeros((512, 128), jnp.float32))
"""


def test_gl015_index_map_out_of_bounds_positive():
    rules, fs = _kern_rules(_OOB_SEED)
    assert "GL015" in rules
    assert any("out-of-bounds" in f.message for f in fs)


def test_gl015_missing_tail_mask_positive():
    """ceil-divided grid with a reachable remainder and no mask in the
    kernel: pad garbage can win the reduction."""
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = jnp.sum(x_ref[...], axis=1, keepdims=True)

        def run(x):
            n = x.shape[0]
            tiles = -(-n // 128)
            xp = jnp.pad(x, ((0, tiles * 128 - n), (0, 0)))
            return pl.pallas_call(
                kern,
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 1), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((tiles * 128, 1),
                                               jnp.float32),
            )(xp)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert "GL015" in rules
    assert any("tail" in f.message for f in fs)


def test_gl015_masked_tail_negative():
    """The same geometry WITH the in-kernel bound mask is clean — the
    fused kernels' own idiom (dist = where(col < n, dist, inf))."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref, *, n):
            i = pl.program_id(0)
            col = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) \\
                + i * 128
            vals = jnp.where(col < n, x_ref[...], 0.0)
            o_ref[...] = jnp.sum(vals, axis=1, keepdims=True)

        def run(x):
            import functools
            n = x.shape[0]
            tiles = -(-n // 128)
            xp = jnp.pad(x, ((0, tiles * 128 - n), (0, 0)))
            return pl.pallas_call(
                functools.partial(kern, n=n),
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 1), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((tiles * 128, 1),
                                               jnp.float32),
            )(xp)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert "GL015" not in rules


def test_gl015_value_clamp_is_not_mask_evidence():
    """A numeric clamp (where(dist < 0, ...)) has an inequality but
    masks nothing positional — it must NOT suppress the missing-tail-
    mask finding (review fix, r6): evidence requires the condition to
    involve an index-derived value (iota/program_id or a name computed
    from one)."""
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            v = jnp.sum(x_ref[...], axis=1, keepdims=True)
            v = jnp.where(v < 0.0, 0.0, v)      # clamp, not a mask
            o_ref[...] = v

        def run(x):
            n = x.shape[0]
            tiles = -(-n // 128)
            xp = jnp.pad(x, ((0, tiles * 128 - n), (0, 0)))
            return pl.pallas_call(
                kern,
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 1), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((tiles * 128, 1),
                                               jnp.float32),
            )(xp)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert "GL015" in rules
    assert any("tail" in f.message for f in fs)


def test_gl015_named_index_mask_negative():
    """The ivf_scan idiom: the mask rides a NAME computed from an iota
    compare (valid = col < size; where(valid, ...)) — evidence."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl
        import functools

        def kern(x_ref, o_ref, *, n):
            i = pl.program_id(0)
            col = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) \\
                + i * 128
            valid = col < n
            o_ref[...] = jnp.sum(jnp.where(valid, x_ref[...], 0.0),
                                 axis=1, keepdims=True)

        def run(x):
            n = x.shape[0]
            tiles = -(-n // 128)
            xp = jnp.pad(x, ((0, tiles * 128 - n), (0, 0)))
            return pl.pallas_call(
                functools.partial(kern, n=n),
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 1), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((tiles * 128, 1),
                                               jnp.float32),
            )(xp)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert "GL015" not in rules


def test_gl015_floor_divided_grid_drops_rows_positive():
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x):
            n = x.shape[0]
            tiles = n // 128
            return pl.pallas_call(
                kern,
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((n, 128), jnp.float32),
            )(x)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert "GL015" in rules
    assert any("never visited" in f.message for f in fs)


def test_gl015_guarded_divisibility_negative():
    """A raise-guard on the remainder prunes the binding — beam_step's
    `if m % g: raise` idiom makes the tail unreachable."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x):
            n = x.shape[0]
            if n % 128:
                raise ValueError("n must be a multiple of 128")
            tiles = n // 128
            return pl.pallas_call(
                kern,
                grid=(tiles,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((n, 128), jnp.float32),
            )(x)

        def caller(x):
            return run(jnp.zeros((300, 128), jnp.float32))
    """)
    assert rules == []


# ---------------------------------------------------------------------------
# GL016 tile-align (kern engine: computed block alignment)
# ---------------------------------------------------------------------------


_MISALIGNED_SEED = """
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def run(x, cols):
    tile = 3 * cols + 1
    return pl.pallas_call(
        kern,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, tile), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, tile), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 4 * tile), jnp.float32),
    )(jnp.zeros((32, 4 * tile), jnp.float32))

def caller(x):
    return run(x, 33)
"""


def test_gl016_computed_misaligned_tile_positive():
    """The acceptance seed class: tile = 3*cols+1 = 100 is COMPUTED —
    invisible to the literal screen, caught by abstract evaluation."""
    rules, fs = _kern_rules(_MISALIGNED_SEED)
    assert "GL016" in rules
    assert any("dim 1 = 100" in f.message for f in fs)


def test_gl016_block_equal_to_array_dim_negative():
    """The real Mosaic rule: a block dim EQUAL to the array dim is
    legal at any size (beam_step's (g, 4, dwq) qrep spec — the old
    literal GL006 needed a suppression for it; the computed audit
    proves it legal)."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = jnp.sum(x_ref[...], axis=1)

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((128, 4, 256),
                                       lambda i: (i, 0, 0))],
                out_specs=pl.BlockSpec((128, 256), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
            )(jnp.zeros((256, 4, 256), jnp.float32))
    """)
    assert "GL016" not in rules


def test_gl016_bf16_sublane_positive():
    """dtype-aware sublane: 8 rows is a legal f32 sublane but OFF the
    (16, 128) bf16 tile — the tile_geometry floor bug this engine
    found in ops/fused_topk.py (fixed r6)."""
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.bfloat16),
            )(jnp.zeros((32, 128), jnp.bfloat16))
    """)
    assert "GL016" in rules
    assert any("bfloat16" in f.message and "sublane" in f.message
               for f in fs)


# ---------------------------------------------------------------------------
# GL017 grid-hazard (kern engine: revisited output refs)
# ---------------------------------------------------------------------------


_ACCUM_SEED = """
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def kern(x_ref, o_ref):
    o_ref[...] = o_ref[...] + jnp.sum(x_ref[...], axis=1, keepdims=True)

def run(x):
    return pl.pallas_call(
        kern,
        grid=(4, 8),
        in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((128, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((512, 128), jnp.float32),
    )(jnp.zeros((512, 1024), jnp.float32))
"""


def test_gl017_uninitialized_accumulator_positive():
    rules, fs = _kern_rules(_ACCUM_SEED)
    assert "GL017" in rules
    assert any("uninitialized" in f.message for f in fs)


def test_gl017_plain_overwrite_positive():
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = jnp.sum(x_ref[...], axis=1, keepdims=True)

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(4, 8),
                in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
                out_specs=pl.BlockSpec((128, 1), lambda i, j: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((512, 1), jnp.float32),
            )(jnp.zeros((512, 1024), jnp.float32))
    """)
    assert "GL017" in rules
    assert any("clobbers" in f.message for f in fs)


def test_gl017_init_guarded_accumulator_negative():
    """The revisiting-safe pattern: first-step init via pl.when, then
    accumulate — no finding."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            @pl.when(pl.program_id(1) == 0)
            def _init():
                o_ref[...] = jnp.zeros_like(o_ref)
            o_ref[...] = o_ref[...] + jnp.sum(x_ref[...], axis=1,
                                              keepdims=True)

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(4, 8),
                in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
                out_specs=pl.BlockSpec((128, 128), lambda i, j: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((512, 128), jnp.float32),
            )(jnp.zeros((512, 1024), jnp.float32))
    """)
    assert "GL017" not in rules


def test_gl017_guard_on_other_ref_does_not_launder():
    """Init-guard evidence is PER REF (review fix, r6): out0's proper
    pl.when init must not suppress out1's uninitialized accumulator."""
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, a_ref, b_ref):
            @pl.when(pl.program_id(1) == 0)
            def _init():
                a_ref[...] = jnp.zeros_like(a_ref)
            a_ref[...] = a_ref[...] + jnp.sum(x_ref[...], axis=1,
                                              keepdims=True)
            b_ref[...] = b_ref[...] + jnp.sum(x_ref[...], axis=1,
                                              keepdims=True)

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(4, 8),
                in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
                out_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, 0)),
                           pl.BlockSpec((128, 128), lambda i, j: (i, 0))],
                out_shape=[
                    jax.ShapeDtypeStruct((512, 128), jnp.float32),
                    jax.ShapeDtypeStruct((512, 128), jnp.float32),
                ],
            )(jnp.zeros((512, 1024), jnp.float32))
    """)
    assert "GL017" in rules
    msgs = [f.message for f in fs if f.rule == "GL017"]
    assert any("'b_ref'" in m for m in msgs)
    assert not any("'a_ref'" in m for m in msgs)


def test_gl017_all_grid_dims_used_negative():
    """An index map consuming every grid dim never revisits — the
    shipped kernels' shape (fused_topk out specs are (i, j))."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def run(x):
            return pl.pallas_call(
                kern,
                grid=(4, 8),
                in_specs=[pl.BlockSpec((128, 128), lambda i, j: (i, j))],
                out_specs=pl.BlockSpec((128, 128), lambda i, j: (i, j)),
                out_shape=jax.ShapeDtypeStruct((512, 1024), jnp.float32),
            )(jnp.zeros((512, 1024), jnp.float32))
    """)
    assert "GL017" not in rules


# ---------------------------------------------------------------------------
# GL018 mxu-dtype (kern engine: in-kernel dot audit)
# ---------------------------------------------------------------------------


def test_gl018_operand_mismatch_positive():
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(a_ref, b_ref, o_ref):
            a = a_ref[...].astype(jnp.bfloat16)
            b = b_ref[...].astype(jnp.float32)
            o_ref[...] = jax.lax.dot_general(
                a, b, dimension_numbers=(((1,), (0,)), ((), ())))

        def run(a, b):
            return pl.pallas_call(
                kern,
                grid=(1,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0)),
                          pl.BlockSpec((128, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
            )(jnp.zeros((128, 128), jnp.float32),
              jnp.zeros((128, 128), jnp.float32))
    """)
    assert "GL018" in rules
    assert any("bfloat16 vs float32" in f.message for f in fs)


def test_gl018_low_precision_accumulator_positive():
    rules, fs = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(a_ref, b_ref, o_ref):
            a = a_ref[...].astype(jnp.bfloat16)
            b = b_ref[...].astype(jnp.bfloat16)
            o_ref[...] = jax.lax.dot_general(
                a, b, dimension_numbers=(((1,), (0,)), ((), ())))

        def run(a, b):
            return pl.pallas_call(
                kern,
                grid=(1,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0)),
                          pl.BlockSpec((128, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
            )(jnp.zeros((128, 128), jnp.float32),
              jnp.zeros((128, 128), jnp.float32))
    """)
    assert "GL018" in rules
    assert any("preferred_element_type" in f.message for f in fs)


def test_gl018_matched_operands_with_preferred_negative():
    """The shipped kernels' idiom: same matmul dtype on both operands +
    f32 accumulation — clean."""
    rules, _ = _kern_rules("""
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(a_ref, b_ref, o_ref):
            a = a_ref[...].astype(jnp.bfloat16)
            b = b_ref[...].astype(jnp.bfloat16)
            o_ref[...] = jax.lax.dot_general(
                a, b, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        def run(a, b):
            return pl.pallas_call(
                kern,
                grid=(1,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0)),
                          pl.BlockSpec((128, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
            )(jnp.zeros((128, 128), jnp.float32),
              jnp.zeros((128, 128), jnp.float32))
    """)
    assert "GL018" not in rules


# ---------------------------------------------------------------------------
# GL008 unclassified swallow
# ---------------------------------------------------------------------------


def test_gl008_swallowed_device_failure_positive():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            try:
                return jnp.sum(x * x)
            except Exception:
                return 0.0
    """)
    assert "GL008" in rules


def test_gl008_bare_except_positive():
    rules, _ = _rules("""
        import jax

        def f(x):
            try:
                jax.block_until_ready(x)
            except:
                pass
    """)
    assert "GL008" in rules


def test_gl008_tuple_except_positive():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            try:
                return jnp.sum(x * x)
            except (ValueError, Exception):
                return 0.0
    """)
    assert "GL008" in rules


def test_gl008_classify_negative():
    rules, _ = _rules("""
        import jax.numpy as jnp
        from raft_tpu import resilience

        def f(x):
            try:
                return jnp.sum(x * x)
            except Exception as e:
                if resilience.classify(e) == "oom":
                    return None
                return 0.0
    """, only="GL008")
    assert rules == []


def test_gl008_reraise_negative():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            try:
                return jnp.sum(x * x)
            except Exception as e:
                raise RuntimeError("wrapped") from e
    """, only="GL008")
    assert rules == []


def test_gl008_no_device_compute_negative():
    rules, _ = _rules("""
        def f(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """, only="GL008")
    assert rules == []


def test_gl008_narrow_except_negative():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            try:
                return jnp.sum(x * x)
            except ValueError:
                return 0.0
    """, only="GL008")
    assert rules == []


def test_gl008_suppressed_with_reason():
    rules, _ = _rules("""
        import jax.numpy as jnp

        def f(x):
            try:
                return jnp.sum(x * x)
            except Exception:  # graft-lint: allow-unclassified-swallow fallback-only probe
                return 0.0
    """)
    assert "GL008" not in rules


# ---------------------------------------------------------------------------
# GL009 unspanned-entry (path-scoped: neighbors/ modules only)
# ---------------------------------------------------------------------------


def _neighbors_rules(src):
    findings = lint_source(textwrap.dedent(src),
                           "raft_tpu/neighbors/fixture.py")
    return [f.rule for f in findings if not f.suppressed]


def test_gl009_unspanned_search_positive():
    rules = _neighbors_rules("""
        def search(params, index, queries, k):
            return index.scan(queries, k)
    """)
    assert "GL009" in rules


def test_gl009_unspanned_build_positive():
    rules = _neighbors_rules("""
        def build_streamed(params, batches):
            return encode(params, batches)
    """)
    assert "GL009" in rules


def test_gl009_entry_span_negative():
    rules = _neighbors_rules("""
        from raft_tpu import obs

        def search(params, index, queries, k):
            with obs.entry_span("search", "demo", queries=len(queries)):
                return index.scan(queries, k)

        def build(params, dataset):
            with obs.span("demo.build"):
                return pack(dataset)
    """)
    assert "GL009" not in rules


def test_gl009_private_and_other_names_exempt():
    rules = _neighbors_rules("""
        def _search_impl(q):
            return q

        def refine(dataset, queries):
            return dataset
    """)
    assert "GL009" not in rules


def test_gl009_outside_neighbors_exempt():
    findings = lint_source(textwrap.dedent("""
        def search(q):
            return q
    """), "raft_tpu/matrix/fixture.py")
    assert "GL009" not in [f.rule for f in findings]


def test_gl009_suppressed_with_reason():
    rules = _neighbors_rules("""
        # graft-lint: allow-unspanned-entry pure parameter arithmetic
        def search_plan(params, k):
            return k * 2
    """)
    assert "GL009" not in rules


# serve/ scope (ISSUE 5): the serving surface is method-shaped, so class
# methods count there — and the prefix set widens to the serving verbs


def _serve_rules(src):
    findings = lint_source(textwrap.dedent(src),
                           "raft_tpu/serve/fixture.py")
    return [f.rule for f in findings if not f.suppressed]


def test_gl009_serve_unspanned_method_positive():
    rules = _serve_rules("""
        class Server:
            def submit(self, queries, k):
                return self.batcher.submit(queries, k)

            def upsert(self, vectors, ids):
                return self.state.upsert(vectors, ids)
    """)
    assert rules.count("GL009") == 2


def test_gl009_serve_fabric_recovery_surface_positive():
    # ISSUE 6: the fabric's recovery control plane (probe/restart) is
    # serving-surface latency too — unspanned probes are a blind spot
    # exactly when the cluster is degraded
    rules = _serve_rules("""
        class Fabric:
            def probe_now(self):
                return {}

            def restart_worker(self, rank):
                return rank
    """)
    assert rules.count("GL009") == 2


def test_gl009_serve_spanned_method_negative():
    rules = _serve_rules("""
        from raft_tpu import obs

        class Server:
            def submit(self, queries, k):
                with obs.span("serve.submit"):
                    return self.batcher.submit(queries, k)

        def publish(name, handle):
            with obs.span("serve.publish", index=name):
                return handle
    """)
    assert "GL009" not in rules


def test_gl009_serve_word_boundary_and_private_exempt():
    # "deleted_rows" is an accounting getter, not the "delete" entry
    # point; private classes/methods are infrastructure
    rules = _serve_rules("""
        class Server:
            def deleted_rows(self):
                return self._n

            def _submit_internal(self, q):
                return q

        class _Handle:
            def search_main(self, q, k):
                return q, k
    """)
    assert "GL009" not in rules


def test_gl009_serve_module_function_positive():
    rules = _serve_rules("""
        def swap_index(name, dataset):
            return rebuild(name, dataset)
    """)
    assert "GL009" in rules


# ---------------------------------------------------------------------------
# GL019 untraced-rpc (ISSUE 13; path-scoped: serve/ + comms/ modules)
# ---------------------------------------------------------------------------


def test_gl019_literal_payload_positive():
    rules = _serve_rules("""
        def fan(group, q, k):
            return group.call(0, "search", {"q": q, "k": k})
    """)
    assert "GL019" in rules


def test_gl019_missing_payload_and_forwarded_method_positive():
    # no payload at all, and a wrapper forwarding a method NAME — both
    # still transport call sites that dropped the context
    rules = _serve_rules("""
        def probe(group, rank):
            return group.call(rank, "ping")

        def forward(group, rank, method, payload=None):
            return group.call(rank, method, payload)
    """)
    assert rules.count("GL019") == 2


def test_gl019_traced_payload_negative():
    rules = _serve_rules("""
        from raft_tpu.obs import trace as obs_trace

        def fan_inline(group, q, ctx):
            return group.call(0, "search",
                              obs_trace.traced_payload({"q": q}, ctx))

        def fan_named(group, q, ctx):
            payload = obs_trace.traced_payload({"q": q}, ctx)
            return group.call(0, "search", payload)

        def fan_literal_field(group, q, wire):
            return group.call(0, "search", {"q": q, "trace": wire})
    """)
    assert "GL019" not in rules


def test_gl019_param_passthrough_still_fires():
    """A payload forwarded through a function parameter is NOT
    evidence: the pass-through site must say where the threading
    happened with a reasoned suppression (fabric._rpc_hedged's shape),
    so the audit trail stays explicit."""
    rules = _serve_rules("""
        def hedged(group, rank, payload):
            return group.call(rank, "search", payload)
    """)
    assert "GL019" in rules
    rules = _serve_rules("""
        def hedged(group, rank, payload):
            # graft-lint: allow-untraced-rpc payload pre-threaded upstream
            return group.call(rank, "search", payload)
    """)
    assert "GL019" not in rules


def test_gl019_non_transport_calls_and_other_paths_exempt():
    # a .call() without the (rank, method) transport shape, and the
    # same code outside serve//comms/ — neither is a finding
    rules = _serve_rules("""
        def other(fn, cb):
            fn.call(cb)
            return cb.call()
    """)
    assert "GL019" not in rules
    findings = lint_source(textwrap.dedent("""
        def fan(group, q):
            return group.call(0, "search", {"q": q})
    """), "raft_tpu/matrix/fixture.py")
    assert "GL019" not in [f.rule for f in findings]


def test_gl019_fires_in_comms_modules_too():
    findings = lint_source(textwrap.dedent("""
        def resync(group, rank, gen):
            return group.call(rank, "publish", {"gen": gen})
    """), "raft_tpu/comms/fixture.py")
    assert "GL019" in [f.rule for f in findings if not f.suppressed]


def test_cli_gl019_acceptance_seed(tmp_path, capsys):
    """ISSUE 13 acceptance seed: a planted untraced data-plane RPC in a
    serve/ module exits rc 1 naming GL019."""
    serve_dir = tmp_path / "serve"
    serve_dir.mkdir()
    (serve_dir / "seeded.py").write_text(
        'def fan(group, q, k):\n'
        '    return group.call(0, "search", {"q": q, "k": k})\n')
    rc = cli_main(["--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "GL019" for f in out["findings"]), out


# ---------------------------------------------------------------------------
# GL023 undocumented-metric (ISSUE 19; catalog contract over
# docs/observability.md)
# ---------------------------------------------------------------------------


_CATALOG = ("| `serve.documented_total` | `index` | fixture |\n"
            "| `serve.filled_ratio{bucket}` | — | label-suffix row |\n")


def _metric_tree(tmp_path, source, catalog=_CATALOG):
    """Plant a raft_tpu/ module beside a docs/observability.md catalog
    and lint it — the GL023 shape: the rule resolves the catalog by
    walking UP from the linted file."""
    pkg = tmp_path / "raft_tpu"
    pkg.mkdir(exist_ok=True)
    if catalog is not None:
        docs = tmp_path / "docs"
        docs.mkdir(exist_ok=True)
        (docs / "observability.md").write_text(catalog)
    mod = pkg / "fixture.py"
    mod.write_text(textwrap.dedent(source))
    findings = lint_file(mod)
    return [f.rule for f in findings if not f.suppressed], findings


def test_gl023_undocumented_positive(tmp_path):
    rules, findings = _metric_tree(tmp_path, """
        from raft_tpu import obs

        def deliver(n):
            obs.counter("serve.phantom_total", n, index="t")
    """)
    assert rules == ["GL023"]
    assert "serve.phantom_total" in findings[0].message


def test_gl023_documented_and_label_suffix_negative(tmp_path):
    # a plain row, a row spelled with its example labels, and the
    # name= kwarg form — all documented, none fire
    rules, _ = _metric_tree(tmp_path, """
        from raft_tpu import obs

        def deliver(n, ratio):
            obs.counter("serve.documented_total", n, index="t")
            obs.observe("serve.filled_ratio", ratio)
            obs.gauge(value=1.0, name="serve.documented_total")
    """)
    assert "GL023" not in rules


def test_gl023_dynamic_name_positive(tmp_path):
    # a name the static check (and the operator's grep) cannot read
    rules, findings = _metric_tree(tmp_path, """
        from raft_tpu import obs

        def deliver(family, n):
            obs.counter(f"serve.{family}_total", n)
    """)
    assert rules == ["GL023"]
    assert "dynamically" in findings[0].message


def test_gl023_suppression(tmp_path):
    rules, findings = _metric_tree(tmp_path, """
        from raft_tpu import obs

        def deliver(n):
            # graft-lint: allow-undocumented-metric internal debug series
            obs.counter("serve.phantom_total", n)
    """)
    assert "GL023" not in rules
    assert any(f.rule == "GL023" and f.suppressed for f in findings)


def test_gl023_bare_emitters_only_inside_obs(tmp_path):
    # inside obs/ the writers are local names; elsewhere a bare
    # counter() is someone else's function
    src = """
        def capture(gauge, counter):
            gauge("serve.phantom_total", 1.0)
            counter("serve.phantom_total")
    """
    rules, _ = _metric_tree(tmp_path, src)
    assert "GL023" not in rules
    obs_pkg = tmp_path / "raft_tpu" / "obs"
    obs_pkg.mkdir()
    mod = obs_pkg / "fixture.py"
    mod.write_text(textwrap.dedent(src))
    findings = lint_file(mod)
    assert [f.rule for f in findings if not f.suppressed] \
        == ["GL023", "GL023"]


def test_gl023_no_catalog_means_no_contract(tmp_path):
    # a detached fixture tree with no docs/observability.md above it
    # has nothing to check against
    rules, _ = _metric_tree(tmp_path, """
        from raft_tpu import obs

        def deliver(n):
            obs.counter("serve.phantom_total", n)
    """, catalog=None)
    assert "GL023" not in rules


def test_gl023_outside_package_exempt():
    findings = lint_source(textwrap.dedent("""
        from raft_tpu import obs

        def deliver(n):
            obs.counter("serve.phantom_total", n)
    """), "serve/fixture.py")
    assert "GL023" not in [f.rule for f in findings]


def test_gl023_catalog_names_must_be_single_line():
    # a name wrapped across a doc line is not greppable and does not
    # document — the drift class the serving section's old prose had
    names = documented_metric_names(
        "| `serve.one_line_total{index}` | ok |\n"
        "prose mention of `serve.wrapped_total{index,\n"
        "action}` spanning a wrap\n")
    assert "serve.one_line_total" in names
    assert not any(n.startswith("serve.wrapped") for n in names)


def test_cli_gl023_acceptance_seed(tmp_path, capsys):
    """ISSUE 19 acceptance seed: a planted undocumented metric emission
    in a raft_tpu/ module exits rc 1 naming GL023."""
    pkg = tmp_path / "raft_tpu"
    pkg.mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(
        "| `serve.documented_total` | `index` | fixture |\n")
    (pkg / "seeded.py").write_text(
        'from raft_tpu import obs\n'
        'def deliver(n):\n'
        '    obs.counter("serve.phantom_total", n, index="t")\n')
    rc = cli_main(["--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "GL023" for f in out["findings"]), out


# ---------------------------------------------------------------------------
# GL024 hand-wired-pipeline (ISSUE 20; serve/comms must dispatch
# through plan.compile)
# ---------------------------------------------------------------------------


def _serve_rules(src, path="raft_tpu/serve/fixture.py"):
    findings = lint_source(textwrap.dedent(src), path)
    return [f.rule for f in findings if not f.suppressed]


def test_gl024_hand_wired_refined_positive():
    rules = _serve_rules("""
        from raft_tpu.neighbors import ivf_pq

        def _dispatch(sp, idx, q, k):
            return ivf_pq.search_refined(sp, idx, q, k, refine_ratio=4)
    """)
    assert "GL024" in rules


def test_gl024_kernel_internal_positive():
    rules = _serve_rules("""
        from raft_tpu.neighbors import ivf_flat

        def _local(q, arrays, k):
            return ivf_flat._ivf_search(q, *arrays, k)
    """, path="raft_tpu/comms/fixture.py")
    assert "GL024" in rules


def test_gl024_plan_dispatch_negative():
    # the same entry point inside a function that compiles a plan is
    # the plan's executor surface, not a hand-wired pipeline
    rules = _serve_rules("""
        from raft_tpu import plan as plan_mod
        from raft_tpu.neighbors import ivf_pq

        def _dispatch(p, idx, q, k, sp):
            cp = plan_mod.compile(p, idx, k=k, search_params=sp)
            if cp is None:
                return ivf_pq.search_refined(sp, idx, q, k)
            return cp(q)
    """)
    assert "GL024" not in rules


def test_gl024_handle_compiled_cache_negative():
    rules = _serve_rules("""
        class _Handle:
            def search_main(self, q, k, rung=None):
                return self.compiled(int(k), rung)(q)
    """)
    assert "GL024" not in rules


def test_gl024_outside_serve_comms_negative():
    # the library entry points themselves (and their tests) are legal —
    # the rule guards the serving dispatch surface only
    rules = _serve_rules("""
        from raft_tpu.neighbors import ivf_pq

        def _helper(sp, idx, q, k):
            return ivf_pq.search_refined(sp, idx, q, k)
    """, path="raft_tpu/neighbors/fixture.py")
    assert "GL024" not in rules


def test_gl024_suppression_with_reason():
    rules = _serve_rules("""
        from raft_tpu.neighbors import brute_force

        def _side_scan(idx, q, k):
            # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path over the side buffer
            return brute_force.search(idx, q, k)
    """)
    assert "GL024" not in rules


def test_cli_gl024_acceptance_seed(tmp_path, capsys):
    """ISSUE 20 acceptance seed: a planted hand-wired serve adapter
    exits rc 1 naming GL024."""
    pkg = tmp_path / "raft_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "seeded.py").write_text(
        "from raft_tpu.neighbors import ivf_pq\n"
        "def _adapter(sp, idx, q, k):\n"
        "    return ivf_pq.search_refined(sp, idx, q, k)\n")
    rc = cli_main(["--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "GL024" for f in out["findings"]), out


# ---------------------------------------------------------------------------
# graft-race engine: GL010-GL014 (ISSUE 7)
# ---------------------------------------------------------------------------


def _race_rules(src, only=None):
    findings = race_lint_source(textwrap.dedent(src), "fixture.py")
    open_f = [f for f in findings if not f.suppressed]
    if only:
        open_f = [f for f in open_f if f.rule == only]
    return [f.rule for f in open_f], open_f


def test_gl010_thread_reachable_read_positive():
    rules, fs = _race_rules("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._t = threading.Thread(target=self._loop, daemon=True)

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            def _loop(self):
                while True:
                    if self._items:
                        return self._items
    """, only="GL010")
    assert rules, fs


def test_gl010_unlocked_write_positive():
    rules, _ = _race_rules("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                self._n = 0
    """, only="GL010")
    assert rules == ["GL010"]


def test_gl010_guarded_by_annotation_positive():
    """An explicit annotation marks the attr guarded even when no
    locked write site exists for the inference to see."""
    rules, _ = _race_rules("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0          #: guarded-by(_lock)

            def reset(self):
                self._n = 0
    """, only="GL010")
    assert rules == ["GL010"]


def test_gl010_negatives():
    """Under the lock, in __init__, in a *_locked caller-holds method,
    or via a Condition aliased to the lock: all clean."""
    rules, fs = _race_rules("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._n = 0
                self._t = threading.Thread(target=self._loop, daemon=True)

            def _loop(self):
                with self._cond:
                    self._n += 1
                    self._drain_locked()

            def _drain_locked(self):
                self._n = 0
    """, only="GL010")
    assert rules == [], fs


def test_gl010_receiver_helper_object_positive():
    """The w.pending-under-w.lock inference: an access to a helper
    object's guarded attr outside its lock is flagged module-wide."""
    rules, _ = _race_rules("""
        import threading

        class _W:
            def __init__(self):
                self.lock = threading.Lock()
                self.pending = {}

        class Group:
            def __init__(self):
                self._t = threading.Thread(target=self._recv, daemon=True)

            def _recv(self):
                w = self._w
                with w.lock:
                    w.pending.pop(1, None)

            def fail(self):
                w = self._w
                w.pending.clear()
    """, only="GL010")
    assert rules == ["GL010"]


def test_gl010_suppressed_with_reason():
    rules, _ = _race_rules("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                self._n = 0  # graft-lint: allow-unguarded-shared-state single-writer init path by construction
    """, only="GL010")
    assert rules == []


def test_gl011_event_check_then_act_positive():
    """The PR-5 compact() single-flight class: Event is_set then set
    with no lock at all."""
    rules, _ = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._busy = threading.Event()

            def compact(self):
                if not self._busy.is_set():
                    self._busy.set()
                    return True
                return False
    """, only="GL011")
    assert rules == ["GL011"]


def test_gl011_cross_region_positive():
    rules, _ = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = {}

            def claim(self, k):
                with self._lock:
                    free = k not in self._jobs
                if free:
                    with self._lock:
                        self._jobs[k] = 1
    """, only="GL011")
    assert rules == ["GL011"]


def test_gl011_negatives():
    """Same critical section, a real test-and-set, and the
    double-checked idiom (fresh re-check in the act's region) are all
    clean."""
    rules, fs = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.compacting = threading.Lock()
                self._jobs = {}
                self._cache = {}

            def same_region(self, k):
                with self._lock:
                    if k not in self._jobs:
                        self._jobs[k] = 1

            def test_and_set(self):
                if not self.compacting.acquire(blocking=False):
                    return None
                return 1

            def double_checked(self, k, build):
                with self._lock:
                    if k in self._cache:
                        return self._cache[k]
                val = build()
                with self._lock:
                    if k in self._cache:
                        return self._cache[k]
                    self._cache[k] = val
                return val
    """, only="GL011")
    assert rules == [], fs


def test_gl012_device_work_under_lock_positive():
    rules, _ = _race_rules("""
        import threading, jax

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def refresh(self, x):
                with self._lock:
                    self._dev = jax.device_put(x)
    """, only="GL012")
    assert rules == ["GL012"]


def test_gl012_build_helper_and_sync_positive():
    rules, _ = _race_rules("""
        import threading
        from raft_tpu.neighbors import brute_force

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def rebuild(self, rows, out):
                with self._lock:
                    self._idx = brute_force.build(rows)
                    out.block_until_ready()
    """, only="GL012")
    assert rules.count("GL012") == 2


def test_gl012_snapshot_then_compute_negative():
    rules, fs = _race_rules("""
        import threading, jax

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def refresh(self, x):
                with self._lock:
                    snap = self._rows
                dev = jax.device_put(snap)
                with self._lock:
                    self._dev = dev
    """, only="GL012")
    assert rules == [], fs


def test_gl013_opposite_nesting_positive_names_cycle():
    rules, fs = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """, only="GL013")
    assert rules == ["GL013"]
    assert "C._a" in fs[0].message and "C._b" in fs[0].message


def test_gl013_one_hop_call_positive():
    """`with a:` calling a method that takes b, vs `with b:` nested a."""
    rules, _ = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._grab_b()

            def _grab_b(self):
                with self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """, only="GL013")
    assert rules == ["GL013"]


def test_gl013_consistent_order_negative():
    rules, _ = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a, self._b:
                    pass
    """, only="GL013")
    assert rules == []


def test_gl014_fire_and_forget_positive():
    rules, _ = _race_rules("""
        import threading

        def go(fn):
            threading.Thread(target=fn).start()
    """, only="GL014")
    assert rules == ["GL014"]


def test_gl014_daemon_and_joined_negative():
    rules, _ = _race_rules("""
        import threading

        def ok(fn):
            threading.Thread(target=fn, daemon=True).start()

        def ok2(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    """, only="GL014")
    assert rules == []


# races engine CLI: the ISSUE-7 planted-bug acceptance seeds


@pytest.mark.parametrize("seed, rule", [
    # planted unguarded write
    ("import threading\n\n\nclass Q:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n"
     "        self._n = 0\n\n"
     "    def bump(self):\n"
     "        with self._lock:\n"
     "            self._n += 1\n\n"
     "    def reset(self):\n"
     "        self._n = 0\n", "GL010"),
    # planted check-then-act
    ("import threading\n\n\nclass C:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n"
     "        self._busy = threading.Event()\n\n"
     "    def compact(self):\n"
     "        if not self._busy.is_set():\n"
     "            self._busy.set()\n", "GL011"),
    # planted device work under lock
    ("import threading, jax\n\n\nclass C:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n\n"
     "    def refresh(self, x):\n"
     "        with self._lock:\n"
     "            self._dev = jax.device_put(x)\n", "GL012"),
])
def test_cli_races_acceptance_seeds(tmp_path, capsys, seed, rule):
    """ISSUE 7 acceptance: each planted concurrency hazard exits 1
    naming its rule under --engine=races."""
    (tmp_path / "seeded.py").write_text(seed)
    rc = cli_main(["--engine=races", "--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == rule for f in out["findings"]), out


def test_cli_engine_comma_list(tmp_path, capsys):
    """--engine=both,races runs all three engines; unknown tokens are a
    usage error (rc 2)."""
    good = tmp_path / "good.py"
    good.write_text("X = 1\n")
    rc = cli_main(["--engine=ast,races", "--format=json", str(tmp_path)])
    capsys.readouterr()
    assert rc == 0
    assert cli_main(["--engine=nope", str(tmp_path)]) == 2


# the tier-1 gate, races half (the ~7s full-tree pass is shared by the
# two gate assertions instead of run twice)


@pytest.fixture(scope="module")
def race_gate_findings():
    return race_lint_paths([PKG])


@pytest.mark.static_analysis
def test_gate_tree_is_race_lint_clean(race_gate_findings):
    open_f = [f for f in race_gate_findings if not f.suppressed]
    assert not open_f, "unsuppressed graft-race findings:\n" + "\n".join(
        f.render() for f in open_f)


@pytest.mark.static_analysis
def test_gate_race_suppressions_all_have_reasons(race_gate_findings):
    for f in race_gate_findings:
        if f.suppressed:
            assert f.reason and f.reason != "(no reason given)", f.render()


# ---------------------------------------------------------------------------
# graft-race v2: whole-program analysis + reconciliation (ISSUE 17)
# ---------------------------------------------------------------------------

# the planted cross-module inversion: Engine.dispatch holds the engine
# lock and publishes into the registry; Registry.refresh holds the
# registry lock and calls back into the engine. Per-file analysis sees
# two clean files — only the whole-program graph closes the cycle.
_XMOD_LIBA = """\
import threading

from libb import Registry


class Engine:
    def __init__(self, reg: "Registry"):
        self._lock = threading.Lock()
        self.reg = reg
        self.jobs = []

    def dispatch(self):
        with self._lock:
            self.reg.publish(self)

    def enqueue(self, x):
        with self._lock:
            self.jobs.append(x)
"""

_XMOD_LIBB = """\
import threading

from liba import Engine


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.table = {}

    def publish(self, eng):
        with self._lock:
            self.table["e"] = eng

    def refresh(self, eng: "Engine"):
        with self._lock:
            eng.enqueue("refresh")
"""


def test_gl013_cross_module_cycle_names_both_files(tmp_path):
    """The ISSUE-17 tentpole acceptance: a lock-order inversion split
    across two modules is invisible to per-file analysis but the
    whole-program graph reports it, naming the full cycle path with
    BOTH files' acquisition sites."""
    (tmp_path / "liba.py").write_text(_XMOD_LIBA)
    (tmp_path / "libb.py").write_text(_XMOD_LIBB)
    findings = race_lint_paths([str(tmp_path)])
    gl13 = [f for f in findings if f.rule == "GL013" and not f.suppressed]
    assert gl13, findings
    msg = gl13[0].message
    assert "whole-program lock-order cycle" in msg
    assert "Engine._lock" in msg and "Registry._lock" in msg
    assert "liba.py" in msg and "libb.py" in msg
    # each file alone is clean — the cycle only exists across them
    for name in ("liba.py", "libb.py"):
        solo = race_lint_paths([str(tmp_path / name)])
        assert not [f for f in solo if f.rule == "GL013"], solo


def test_whole_program_reentrant_reacquire_is_not_an_edge(tmp_path):
    """Calling a method that re-acquires an RLock the caller already
    holds must not manufacture graph edges (mirrors the sanitizer:
    reentrant depth>1 never records an acquisition)."""
    (tmp_path / "re.py").write_text(textwrap.dedent("""\
        import threading


        class S:
            def __init__(self):
                self._lock = threading.RLock()
                self._aux = threading.Lock()
                self.n = 0

        def outer(s: "S"):
            with s._lock:
                with s._aux:
                    helper(s)

        def helper(s: "S"):
            with s._lock:
                s.n += 1
    """))
    # helper re-acquires s._lock while outer already holds it: without
    # the reentrancy guard the expansion adds aux -> lock, a false
    # cycle against outer's real lock -> aux order
    findings = race_lint_paths([str(tmp_path)])
    assert not [f for f in findings if f.rule == "GL013"], findings


def test_gl020_leaked_acquire_on_early_return():
    """ISSUE-17 acceptance: a manual acquire whose release is skipped
    on an early return is flagged at the acquire site."""
    rules, fs = _race_rules("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.free = []

            def take(self):
                self._lock.acquire()
                if not self.free:
                    return None
                x = self.free.pop()
                self._lock.release()
                return x
    """, only="GL020")
    assert rules == ["GL020"]
    assert "leak" in fs[0].message
    assert fs[0].line == 10          # the acquire, not the return


def test_gl020_fall_through_exit_positive():
    rules, _ = _race_rules("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def grab(self):
                self._lock.acquire()
    """, only="GL020")
    # acquire-named methods are the ownership-transfer idiom and exempt;
    # a differently-named method falling off the end is a leak
    assert rules == ["GL020"]


def test_gl020_negatives():
    rules, fs = _race_rules("""
        import threading
        from raft_tpu.analysis.lockwatch import make_flag_lock

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._flag = make_flag_lock("c.flag")
                self.items = []

            def balanced_finally(self):
                self._lock.acquire()
                try:
                    return self.items.pop() if self.items else None
                finally:
                    self._lock.release()

            def try_start(self):
                # flag locks are try-acquire handoffs: exempt
                return self._flag.acquire(False)

            def probe(self):
                # nonblocking try-acquire with both-branch handling
                if self._lock.acquire(blocking=False):
                    self._lock.release()
                    return True
                return False

            def with_stmt(self):
                with self._lock:
                    return list(self.items)

            def acquire(self):
                # *named* acquire: ownership transfers to the caller
                self._lock.acquire()
    """, only="GL020")
    assert rules == [], fs


def test_gl020_suppressed_with_reason():
    findings = race_lint_source(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def handoff(self):
                # graft-lint: allow-unbalanced-acquire released by the worker's finally
                self._lock.acquire()
    """), "fixture.py")
    gl20 = [f for f in findings if f.rule == "GL020"]
    assert gl20 and gl20[0].suppressed
    assert "worker" in gl20[0].reason


def test_cli_reconcile_gl022_hard_and_gl021_advisory(tmp_path, capsys):
    """--reconcile: a runtime edge absent from the static model is a
    hard GL022 anchored at the artifact; a modeled edge no test
    exercised is an advisory GL021 that does NOT gate."""
    (tmp_path / "mod.py").write_text(textwrap.dedent("""\
        import threading


        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def nest(self):
                with self.a:
                    with self.b:
                        pass
    """))
    art = tmp_path / "runtime.json"
    art.write_text(json.dumps({
        "graph": {"S.a": {"ghost.lock": "observed at runtime"}}}))
    rc = cli_main(["--engine=races", "--format=json",
                   f"--reconcile={art}", str(tmp_path / "mod.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    gl22 = [f for f in out["findings"] if f["rule"] == "GL022"]
    assert gl22 and str(art) == gl22[0]["path"]
    assert "ghost.lock" in gl22[0]["message"]
    # static S.a -> S.b never observed: advisory only
    gl21 = [f for f in out["advisory"] if f["rule"] == "GL021"]
    assert gl21 and "S.b" in gl21[0]["message"]

    # artifact matching the model exactly: rc 0, nothing at all
    art.write_text(json.dumps({"graph": {"S.a": {"S.b": "site"}}}))
    rc = cli_main(["--engine=races", "--format=json",
                   f"--reconcile={art}", str(tmp_path / "mod.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["counts"] == {"open": 0, "advisory": 0, "suppressed": 0}


@pytest.mark.static_analysis
def test_reconcile_shipped_tree_against_runtime_artifact(capsys):
    """ISSUE-17 acceptance: every edge the threadsan suites actually
    observed (LOCKGRAPH_r17.json, exported by lockwatch under
    RAFT_TPU_THREADSAN_EXPORT) is present in the static whole-program
    model — zero GL022."""
    art = os.path.join(REPO, "LOCKGRAPH_r17.json")
    findings = race_lint_paths([PKG], reconcile=art)
    gl22 = [f for f in findings if f.rule == "GL022"]
    assert not gl22, "static lock model lost runtime edges:\n" + \
        "\n".join(f.render() for f in gl22)


def test_cli_strict_suppressions_flags_stale_only(tmp_path, capsys):
    """--strict-suppressions: a marker that suppresses nothing is
    GL000; a live one is untouched; markers for rules whose engine did
    NOT run this invocation are never judged."""
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent("""\
        import jax.numpy as jnp


        def live(x):
            return float(jnp.sum(x))  # graft-lint: allow-host-sync reduction is the result


        def stale(x):
            return x + 1  # graft-lint: allow-host-sync nothing syncs here


        def other_engine(x):
            return x  # graft-lint: allow-unguarded-shared-state races engine not run
    """))
    rc = cli_main(["--engine=ast", "--strict-suppressions",
                   "--format=json", str(f)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    gl0 = [x for x in out["findings"] if x["rule"] == "GL000"]
    assert len(gl0) == 1, out
    assert gl0[0]["line"] == 9
    assert "allow-host-sync" in gl0[0]["message"]
    # without the flag the stale marker is inert, not an error
    assert cli_main(["--engine=ast", str(f)]) == 0
    capsys.readouterr()


@pytest.mark.static_analysis
def test_gate_tree_has_no_stale_suppressions():
    """Satellite: the shipped tree holds zero stale markers under the
    full static gate (jaxpr excluded: its findings anchor to
    <jaxpr:entry> pseudo-paths, so source markers can never cover
    them and its rules are judged via the ast run)."""
    rc = cli_main(["--engine=ast,races,kern", "--strict-suppressions",
                   "--format=json", PKG])
    assert rc == 0


def test_cli_emit_lock_hierarchy(capsys):
    """--emit-lock-hierarchy prints the markdown hierarchy the serving
    docs embed, derived from the same whole-program summaries."""
    rc = cli_main(["--emit-lock-hierarchy", PKG])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serve.mutation" in out
    assert "fabric.swap" in out


# ---------------------------------------------------------------------------
# lint-baseline drift (ISSUE 17 satellite: LINT_r17.json)
# ---------------------------------------------------------------------------


def _suppressed_per_rule(findings):
    counts: dict = {}
    for f in findings:
        if f.suppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


@pytest.mark.static_analysis
def test_lint_baseline_drift(race_gate_findings, kern_gate_findings):
    """The committed `graft-lint --format=json --engine=all` baseline
    (LINT_r17.json) is the reviewed gate state: zero open findings, and
    a fixed per-rule suppression budget. New findings fail the other
    gate tests; this one fails when the SUPPRESSION count grows — a new
    `allow-` marker snuck in without the baseline being regenerated
    (and therefore without the baseline diff showing up in review).
    Shrinking is fine (stale markers removed). jaxpr-engine rules are
    compared too — their findings anchor to pseudo-paths no marker can
    cover, so their budget is structurally zero.

    Regenerate after a reviewed suppression change:
    `python scripts/graft_lint.py --format=json --engine=all raft_tpu/ > LINT_r17.json`
    """
    with open(os.path.join(REPO, "LINT_r17.json")) as fh:
        base = json.load(fh)
    assert base["counts"]["open"] == 0, \
        "baseline itself must be clean — regenerate from a clean tree"

    base_counts: dict = {}
    for f in base["suppressed"]:
        base_counts[f["rule"]] = base_counts.get(f["rule"], 0) + 1

    current = _suppressed_per_rule(lint_paths([PKG])
                                   + race_gate_findings
                                   + kern_gate_findings)
    grew = {r: (base_counts.get(r, 0), n) for r, n in current.items()
            if n > base_counts.get(r, 0)}
    assert not grew, (
        "suppression budget exceeded without regenerating LINT_r17.json "
        f"(rule: baseline -> current): {grew}")


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_same_line_and_line_above():
    src = """
        import jax.numpy as jnp

        def a(x):
            return float(jnp.max(x))  # graft-lint: allow-host-sync scalar epsilon

        def b(x):
            # graft-lint: allow-host-sync certification loop by design
            return float(jnp.max(x))
    """
    findings = lint_source(textwrap.dedent(src), "fixture.py")
    assert all(f.suppressed for f in findings if f.rule == "GL001")
    assert sum(f.rule == "GL001" for f in findings) == 2


def test_bare_suppression_reported():
    rules, fs = _rules("""
        import jax.numpy as jnp

        def a(x):
            return float(jnp.max(x))  # graft-lint: allow-host-sync
    """)
    assert "GL000" in rules            # reason missing
    assert "GL001" not in rules        # ...but the suppression still applies


def test_unknown_slug_reported():
    rules, _ = _rules("""
        X = 1  # graft-lint: allow-no-such-rule because reasons
    """)
    assert rules == ["GL000"]


def test_suppression_inside_string_literal_is_inert():
    """Documentation quoting the syntax must not register a live
    suppression for the next line."""
    rules, _ = _rules('''
        import jax.numpy as jnp

        DOC = """example: x = 1  # graft-lint: allow-host-sync build"""
        Y = float(jnp.asarray(2.0))
    ''')
    assert rules == ["GL001"]          # NOT suppressed by the docstring


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_json_exit_nonzero_on_seeded_bug(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax, jax.numpy as jnp

        @jax.jit
        def hot(x):
            return x + x.max().item()
    """))
    rc = cli_main(["--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "GL001" for f in out["findings"])


def test_cli_exit_zero_on_clean_file(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text("import jax.numpy as jnp\n\n\ndef f(x):\n    return x\n")
    assert cli_main(["--format=json", str(tmp_path)]) == 0


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


@pytest.mark.parametrize("seed, rule", [
    ("import jax\n\n@jax.jit\ndef hot(x):\n    return x.sum().item()\n",
     "GL001"),
    ('def search():\n    """Serves 12.5k QPS on SIFT-1M."""\n', "GL005"),
    ("import jax, jax.numpy as jnp\n\ndef f(ids, k):\n"
     "    return jax.lax.top_k(ids.astype(jnp.float32), k)\n", "GL003"),
])
def test_cli_acceptance_seeds(tmp_path, capsys, seed, rule):
    """ISSUE acceptance: each seeded hazard class exits nonzero naming
    its rule."""
    (tmp_path / "seeded.py").write_text(seed)
    rc = cli_main(["--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == rule for f in out["findings"]), out


# ---------------------------------------------------------------------------
# the tier-1 gate (AST half; jaxpr half in test_jaxpr_audit.py)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# graft-kern CLI acceptance seeds + the kern gate
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed, rule", [
    (_OOB_SEED, "GL015"),
    (_MISALIGNED_SEED, "GL016"),
    (_ACCUM_SEED, "GL017"),
])
def test_cli_kern_acceptance_seeds(tmp_path, capsys, seed, rule):
    """ISSUE 10 acceptance: each planted kernel bug (OOB index map /
    misaligned computed tile / unsafe grid accumulator) exits rc 1
    naming its rule under --engine=kern."""
    (tmp_path / "seeded.py").write_text(seed)
    rc = cli_main(["--engine=kern", "--format=json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == rule and f["engine"] == "kern"
               for f in out["findings"]), out


def test_cli_engine_kern_and_all_spellings(tmp_path, capsys):
    """--engine=kern is comma-composable and included in 'all'."""
    good = tmp_path / "good.py"
    good.write_text("def f(x):\n    return x\n")
    assert cli_main(["--engine=kern", "--format=json", str(tmp_path)]) == 0
    capsys.readouterr()
    assert cli_main(["--engine=ast,kern", "--format=json",
                     str(tmp_path)]) == 0
    capsys.readouterr()
    (tmp_path / "seeded.py").write_text(_OOB_SEED)
    rc = cli_main(["--engine=ast,races,kern", "--format=json",
                   str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "GL015" for f in out["findings"]), out


@pytest.fixture(scope="module")
def kern_gate_findings():
    return kern_lint_paths([PKG])


@pytest.mark.static_analysis
def test_gate_tree_is_kernel_clean(kern_gate_findings):
    """ISSUE 10 acceptance: graft-lint --engine=kern raft_tpu/ runs
    clean — 0 open findings, reasoned suppressions only."""
    open_f = [f for f in kern_gate_findings if not f.suppressed]
    assert not open_f, "unsuppressed graft-kern findings:\n" + "\n".join(
        f.render() for f in open_f)


@pytest.mark.static_analysis
def test_gate_kern_suppressions_all_have_reasons(kern_gate_findings):
    for f in kern_gate_findings:
        if f.suppressed:
            assert f.reason and f.reason != "(no reason given)", f.render()


@pytest.mark.static_analysis
def test_gate_engine_all_includes_kern(tmp_path, capsys):
    """The 'all' gate = every engine; a planted kernel bug must fail it
    even when the other engines are clean."""
    (tmp_path / "seeded.py").write_text(_ACCUM_SEED)
    rc = cli_main(["--engine=ast,races,kern", "--format=json",
                   str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["engine"] for f in out["findings"]} == {"kern"}, out


@pytest.mark.static_analysis
def test_gate_tree_is_lint_clean():
    findings = lint_paths([PKG])
    open_f = [f for f in findings if not f.suppressed]
    assert not open_f, "unsuppressed graft-lint findings:\n" + "\n".join(
        f.render() for f in open_f)


@pytest.mark.static_analysis
def test_gate_suppressions_all_have_reasons():
    findings = lint_paths([PKG])
    for f in findings:
        if f.suppressed:
            assert f.reason and f.reason != "(no reason given)", f.render()
