"""single_linkage tests — scipy.cluster.hierarchy / sklearn oracles
(mirrors cpp/test/cluster/linkage.cu: known-blob labelings + dendrogram
height parity)."""

import numpy as np
import pytest
from scipy.cluster.hierarchy import fcluster, linkage
from sklearn.metrics import adjusted_rand_score

from raft_tpu.cluster import single_linkage


def _blobs(n, d, k, seed, spread=0.05):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-5, 5, (k, d))
    x = centers[rng.integers(0, k, n)] + rng.normal(0, spread, (n, d))
    return x.astype(np.float32)


@pytest.mark.parametrize("connectivity", ["knn", "pairwise"])
def test_blobs_exact_labels(connectivity):
    x = _blobs(300, 4, 5, seed=0)
    out = single_linkage(x, n_clusters=5, metric="euclidean",
                         connectivity=connectivity)
    want = fcluster(linkage(x, method="single"), 5, criterion="maxclust")
    assert adjusted_rand_score(want, out.labels) == 1.0


def test_dendrogram_heights_match_scipy():
    x = _blobs(120, 3, 3, seed=1, spread=0.3)
    out = single_linkage(x, n_clusters=3, metric="euclidean",
                         connectivity="pairwise")
    z = linkage(x, method="single")
    # single-linkage merge heights are unique up to ties; the sorted
    # sequence must match scipy's third column
    np.testing.assert_allclose(
        np.sort(out.deltas), np.sort(z[:, 2]), rtol=1e-3
    )
    # sizes: final merge must cover all points
    assert out.sizes[-1] == 120
    assert out.children.shape == (119, 2)


def test_knn_connectivity_disconnected_repair():
    # two far-apart tight blobs with small k: KNN graph is disconnected,
    # the cross-component repair must still produce a full dendrogram
    rng = np.random.default_rng(2)
    a = rng.normal(0, 0.1, (40, 3))
    b = rng.normal(50, 0.1, (40, 3))
    x = np.vstack([a, b]).astype(np.float32)
    out = single_linkage(x, n_clusters=2, metric="euclidean",
                         connectivity="knn", c=5)
    labels = out.labels
    assert len(np.unique(labels)) == 2
    assert len(np.unique(labels[:40])) == 1
    assert len(np.unique(labels[40:])) == 1
    assert labels[0] != labels[40]


def test_n_clusters_sweep():
    x = _blobs(200, 5, 4, seed=3)
    for k in (2, 3, 4, 8):
        out = single_linkage(x, n_clusters=k, connectivity="knn", c=10)
        assert len(np.unique(out.labels)) == k


def test_sqeuclidean_metric():
    x = _blobs(150, 4, 3, seed=4)
    out = single_linkage(x, n_clusters=3, metric="sqeuclidean",
                         connectivity="knn")
    want = fcluster(linkage(x, method="single"), 3, criterion="maxclust")
    assert adjusted_rand_score(want, out.labels) == 1.0
