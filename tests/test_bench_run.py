"""Bench orchestration smoke test (config-driven runner, groundtruth
cache, CSV + plot export — raft-ann-bench analog)."""

import json
import os

import numpy as np

from raft_tpu.bench import run as bench_run


def test_smoke_config(tmp_path):
    cfg = json.load(open("raft_tpu/bench/conf/smoke.json"))
    cfg["dataset"]["synthetic"]["n"] = 5000
    cfg["dataset"]["synthetic"]["n_queries"] = 100
    results = bench_run.run_config(cfg, iters=2)
    assert len(results) == 3  # bf + 2 ivf search param sets
    bf = results[0]
    assert bf.recall > 0.999  # exact method
    assert all(r.qps > 0 for r in results)
    # ivf recall grows with n_probes
    assert results[2].recall >= results[1].recall - 1e-6
    # exports
    from raft_tpu.bench.harness import export_csv

    csv_path = str(tmp_path / "out.csv")
    export_csv(results, csv_path)
    assert os.path.getsize(csv_path) > 0
    png = str(tmp_path / "out.png")
    bench_run.plot_results(results, png)
    assert os.path.getsize(png) > 0


def test_groundtruth_cache(tmp_path):
    rng = np.random.default_rng(0)
    base = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((50, 16)).astype(np.float32)
    cache = str(tmp_path / "gt")
    cfg = {"distance": "sqeuclidean", "groundtruth_cache": cache}
    gt1 = bench_run.get_groundtruth(cfg, base, q, 10)
    assert os.path.exists(cache + ".neighbors.ibin")
    gt2 = bench_run.get_groundtruth(cfg, base, q, 10)
    np.testing.assert_array_equal(gt1, gt2)
    # oracle: exact
    d = ((q[:, None, :] - base[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d, 1)[:, :10]
    overlap = np.mean([
        len(set(gt1[i]) & set(want[i])) / 10 for i in range(50)
    ])
    assert overlap > 0.99


def test_chunked_groundtruth():
    rng = np.random.default_rng(1)
    base = rng.standard_normal((3000, 8)).astype(np.float32)
    q = rng.standard_normal((40, 8)).astype(np.float32)
    gt = bench_run.generate_groundtruth(base, q, 5, "sqeuclidean", chunk=1000)
    d = ((q[:, None, :] - base[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d, 1)[:, :5]
    overlap = np.mean([len(set(gt[i]) & set(want[i])) / 5 for i in range(40)])
    assert overlap > 0.99


def test_constraints_skip_invalid_cases():
    from raft_tpu.bench.constraints import check_case

    assert check_case("cagra", {"graph_degree": 32}, {"itopk_size": 64},
                      128, 10)
    assert not check_case("cagra", {"graph_degree": 64,
                                    "intermediate_graph_degree": 32}, {},
                          128, 10)
    assert not check_case("cagra", {"graph_degree": 64},
                          {"search_width": 8}, 128, 10)
    assert not check_case("ivf_pq", {"n_lists": 64}, {"n_probes": 128}, 96,
                          10)
    assert check_case("ivf_flat", {"n_lists": 64}, {"n_probes": 64}, 96, 10)
    assert not check_case("ivf_pq", {"pq_dim": 200}, {}, 96, 10)


def test_run_config_skips_invalid_cases(capsys):
    """The orchestrator itself gates on constraints: invalid search
    params are skipped (printed), valid ones still run — the reference
    sweep pattern (raft-ann-bench constraints/__init__.py)."""
    cfg = json.load(open("raft_tpu/bench/conf/smoke.json"))
    cfg["dataset"]["synthetic"]["n"] = 3000
    cfg["dataset"]["synthetic"]["n_queries"] = 50
    # poison one index def with an impossible probe count + keep a valid one
    for idx in cfg["index"]:
        if idx["algo"] == "ivf_flat":
            idx["search_params"] = (
                [{"n_probes": 10**6}] + idx["search_params"][:1]
            )
    results = bench_run.run_config(cfg, iters=2)
    out = capsys.readouterr().out
    assert "skip invalid case" in out
    assert len(results) == 2  # bf + the one valid ivf case
    assert all(r.qps > 0 for r in results)


def test_latency_mode(tmp_path):
    """--mode latency: per-call p50/p95 at batch 1/10 in extra, qps
    derived from batch-10 p50."""
    cfg = json.load(open("raft_tpu/bench/conf/smoke.json"))
    cfg["dataset"]["synthetic"]["n"] = 3000
    cfg["dataset"]["synthetic"]["n_queries"] = 64
    cfg["index"] = [i for i in cfg["index"] if i["algo"] == "ivf_flat"]
    cfg["index"][0]["search_params"] = cfg["index"][0]["search_params"][:1]
    results = bench_run.run_config(cfg, iters=3, mode="latency")
    assert len(results) == 1
    r = results[0]
    assert r.extra["mode"] == "latency"
    for key in ("lat.b1.p50", "lat.b1.p95", "lat.b10.p50", "lat.b10.p95"):
        assert r.extra[key] > 0
    assert r.extra["lat.b1.p50"] <= r.extra["lat.b1.p95"]
    # extra stores p50 rounded to 6 decimals; compare loosely
    assert abs(r.qps - 10.0 / r.extra["lat.b10.p50"]) / r.qps < 1e-3
