import numpy as np
import pytest

from raft_tpu.distance import fused_l2_nn_argmin, masked_l2_nn_argmin
from tests.oracles import naive_pairwise


@pytest.mark.parametrize("m,n,d", [(100, 37, 16), (257, 1000, 64)])
@pytest.mark.parametrize("sqrt", [False, True])
def test_fused_l2_nn(rng, m, n, d, sqrt):
    x = rng.standard_normal((m, d)).astype(np.float32)
    y = rng.standard_normal((n, d)).astype(np.float32)
    val, idx = fused_l2_nn_argmin(x, y, sqrt=sqrt)
    val, idx = np.asarray(val), np.asarray(idx)
    dist = naive_pairwise(x, y, "sqeuclidean")
    want_idx = dist.argmin(axis=1)
    want_val = dist.min(axis=1)
    if sqrt:
        want_val = np.sqrt(want_val)
    np.testing.assert_array_equal(idx, want_idx)
    np.testing.assert_allclose(val, want_val, rtol=1e-3, atol=1e-3)


def test_fused_l2_nn_tiled_matches(rng):
    x = rng.standard_normal((64, 24)).astype(np.float32)
    y = rng.standard_normal((999, 24)).astype(np.float32)
    val_t, idx_t = fused_l2_nn_argmin(x, y, tile_n=128)
    val_f, idx_f = fused_l2_nn_argmin(x, y)
    np.testing.assert_array_equal(np.asarray(idx_t), np.asarray(idx_f))
    np.testing.assert_allclose(np.asarray(val_t), np.asarray(val_f), rtol=1e-5)


def test_masked_l2_nn(rng):
    m, n, d = 40, 60, 8
    x = rng.standard_normal((m, d)).astype(np.float32)
    y = rng.standard_normal((n, d)).astype(np.float32)
    adj = rng.random((m, n)) < 0.5
    adj[:, 0] = True  # no empty rows
    val, idx = masked_l2_nn_argmin(x, y, adj)
    dist = naive_pairwise(x, y, "sqeuclidean")
    dist[~adj] = np.inf
    np.testing.assert_array_equal(np.asarray(idx), dist.argmin(axis=1))
