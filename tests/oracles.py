"""Numpy reference oracles for tests.

Analog of the reference's naive-KNN oracle + recall-bound evaluation
(cpp/internal/raft_internal/neighbors/naive_knn.cuh:31-90,
cpp/test/neighbors/ann_utils.cuh:155,218 eval_neighbours/eval_recall).
"""

from __future__ import annotations

import numpy as np


def naive_pairwise(x: np.ndarray, y: np.ndarray, metric: str, p: float = 2.0) -> np.ndarray:
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    xi = x[:, None, :]
    yi = y[None, :, :]
    if metric == "sqeuclidean":
        return ((xi - yi) ** 2).sum(-1)
    if metric in ("euclidean", "l2"):
        return np.sqrt(((xi - yi) ** 2).sum(-1))
    if metric in ("l1", "cityblock"):
        return np.abs(xi - yi).sum(-1)
    if metric in ("chebyshev", "linf"):
        return np.abs(xi - yi).max(-1)
    if metric == "inner_product":
        return x @ y.T
    if metric == "cosine":
        xn = np.linalg.norm(x, axis=1)
        yn = np.linalg.norm(y, axis=1)
        return 1.0 - (x @ y.T) / np.maximum(np.outer(xn, yn), 1e-300)
    if metric == "correlation":
        xc = x - x.mean(1, keepdims=True)
        yc = y - y.mean(1, keepdims=True)
        return 1.0 - (xc @ yc.T) / np.maximum(
            np.outer(np.linalg.norm(xc, axis=1), np.linalg.norm(yc, axis=1)), 1e-300
        )
    if metric == "canberra":
        num = np.abs(xi - yi)
        den = np.abs(xi) + np.abs(yi)
        return np.where(den == 0, 0.0, num / np.where(den == 0, 1, den)).sum(-1)
    if metric == "minkowski":
        return (np.abs(xi - yi) ** p).sum(-1) ** (1.0 / p)
    if metric == "braycurtis":
        num = np.abs(xi - yi).sum(-1)
        den = np.abs(xi + yi).sum(-1)
        return np.where(den == 0, 0.0, num / np.where(den == 0, 1, den))
    if metric == "hamming":
        return (xi != yi).mean(-1)
    if metric == "jensenshannon":
        m = 0.5 * (xi + yi)
        def xlogx(a, b):
            with np.errstate(divide="ignore", invalid="ignore"):
                r = a * (np.log(a) - np.log(b))
            return np.where((a == 0) | (b == 0), 0.0, r)
        return np.sqrt(np.maximum(0.5 * (xlogx(xi, m) + xlogx(yi, m)).sum(-1), 0))
    if metric == "kl_divergence":
        with np.errstate(divide="ignore", invalid="ignore"):
            r = xi * (np.log(xi) - np.log(yi))
        return 0.5 * np.where(xi == 0, 0.0, r).sum(-1)
    if metric == "hellinger":
        dot = np.sqrt(xi * yi).sum(-1)
        return np.sqrt(np.maximum(1.0 - dot, 0.0))
    if metric == "russellrao":
        d = x.shape[1]
        return (d - x @ y.T) / d
    if metric == "jaccard":
        dot = x @ y.T
        union = x.sum(1)[:, None] + y.sum(1)[None, :] - dot
        return 1.0 - dot / np.where(union == 0, 1.0, union)
    if metric == "dice":
        dot = x @ y.T
        den = x.sum(1)[:, None] + y.sum(1)[None, :]
        return 1.0 - 2 * dot / np.where(den == 0, 1.0, den)
    if metric == "haversine":
        lat1, lon1 = xi[..., 0], xi[..., 1]
        lat2, lon2 = yi[..., 0], yi[..., 1]
        a = np.sin(0.5 * (lat1 - lat2)) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(0.5 * (lon1 - lon2)) ** 2
        return 2 * np.arcsin(np.sqrt(np.clip(a, 0, 1)))
    raise ValueError(metric)


def naive_knn(x: np.ndarray, y: np.ndarray, k: int, metric: str = "sqeuclidean"):
    """Exact KNN oracle: returns (dist [m,k], idx [m,k])."""
    d = naive_pairwise(x, y, metric)
    if metric == "inner_product":
        idx = np.argsort(-d, axis=1, kind="stable")[:, :k]
    else:
        idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    dist = np.take_along_axis(d, idx, axis=1)
    return dist, idx


def eval_recall(found_idx: np.ndarray, true_idx: np.ndarray) -> float:
    """Set-intersection recall@k (reference ann_utils.cuh:218 eval_recall)."""
    n, k = true_idx.shape
    hits = 0
    for i in range(n):
        hits += len(set(found_idx[i, :k].tolist()) & set(true_idx[i].tolist()))
    return hits / (n * k)


def eval_neighbours(found_idx, true_idx, found_dist, true_dist, eps: float = 1e-3) -> float:
    """Distance-aware recall: a found neighbor also counts if its distance
    ties the true k-th distance (reference ann_utils.cuh:155)."""
    n, k = true_idx.shape
    hits = 0
    for i in range(n):
        true_set = set(true_idx[i].tolist())
        kth = true_dist[i, -1]
        for j in range(k):
            if found_idx[i, j] in true_set or found_dist[i, j] <= kth + eps:
                hits += 1
    return hits / (n * k)
