"""Tiered-memory rerank tests (ISSUE 12, marker ``tiered``).

Covers: shortlist-only host/memmap rerank bitwise-identical to the
full-upload ``dataset=`` path (with and without the HBM hot-row
cache), clock/second-chance residency (hits, promotions, evictions,
hit-rate under a skewed query mix), dedup-honest bytes accounting
(valid slots only; unique rows on the host tier), prefilter
composition, ``search_refined`` back-compat routing, the serve
integration (tiered adapter bitwise vs full-upload serving, result
cache hit/invalidation, post-warmup trace stability), memmap-backed
streaming end-to-end (``build_streamed`` + ``search_file`` with
kill-and-resume faultinject drills), the ``oom@chunk`` ladder over a
tiered search, and the sharded ``rerank_source`` composition."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import obs, serve, tuning
from raft_tpu.neighbors import ivf_pq, tiered
from raft_tpu.neighbors.refine import refine
from raft_tpu.neighbors.stream import search_file, search_host_array
from raft_tpu.resilience import faultinject

pytestmark = pytest.mark.tiered

_N, _D, _K = 2000, 32, 10


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_OBS", raising=False)
    obs.set_mode(None)
    obs.reset()
    faultinject.clear()
    yield
    obs.reset()
    obs.set_mode(None)
    faultinject.clear()
    tuning.reload()


def _value(snap, name, /, **labels):
    want = {str(k): str(v) for k, v in labels.items()}
    for p in snap["metrics"].get(name, {}).get("points", []):
        if all(p["labels"].get(k) == v for k, v in want.items()):
            return p.get("value")
    return None


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    ds = rng.standard_normal((_N, _D)).astype(np.float32)
    q = rng.standard_normal((40, _D)).astype(np.float32)
    return ds, q


@pytest.fixture(scope="module")
def pq_index(data):
    ds, _ = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)
    return ivf_pq.build(params, ds)


@pytest.fixture(scope="module")
def sp():
    return ivf_pq.SearchParams(n_probes=16)


# ---------------------------------------------------------------------------
# bitwise identity: tiered shortlist-only fetch vs full-upload rerank
# ---------------------------------------------------------------------------


def test_host_source_bitwise_vs_full_upload(data, pq_index, sp):
    """The acceptance bar: a host numpy dataset= (shortlist-only fetch)
    returns bitwise-identical (d, ids) to the device full-upload path
    on the same shortlist."""
    ds, q = data
    d_dev, i_dev = ivf_pq.search_refined(sp, pq_index, q, _K,
                                         refine_ratio=4,
                                         dataset=jnp.asarray(ds))
    d_host, i_host = ivf_pq.search_refined(sp, pq_index, q, _K,
                                           refine_ratio=4, dataset=ds)
    assert np.array_equal(np.asarray(d_dev), np.asarray(d_host))
    assert np.array_equal(np.asarray(i_dev), np.asarray(i_host))


def test_hot_cache_stays_bitwise(data, pq_index, sp):
    """Residency must never change answers: repeated batches served
    increasingly from the HBM hot-row cache stay bitwise identical to
    the full-upload rerank, through promotions AND evictions (a tiny
    capacity forces clock churn)."""
    ds, q = data
    d_dev, i_dev = ivf_pq.search_refined(sp, pq_index, q, _K,
                                         refine_ratio=4,
                                         dataset=jnp.asarray(ds))
    for hot_rows in (16, 512):        # churning and comfortably-resident
        src = tiered.HostArraySource(ds, hot_rows=hot_rows,
                                     promote_after=1)
        for _ in range(3):
            d_t, i_t = ivf_pq.search_refined(sp, pq_index, q, _K,
                                             refine_ratio=4, dataset=src)
            assert np.array_equal(np.asarray(d_dev), np.asarray(d_t))
            assert np.array_equal(np.asarray(i_dev), np.asarray(i_t))
        st = src.stats()
        assert st["hbm_hits"] > 0        # the cache actually served rows
        if hot_rows == 16:
            assert st["evictions"] > 0   # and the clock actually churned


def test_memmap_source_bitwise(data, pq_index, sp, tmp_path):
    """np.memmap originals (the SSD tier) behave exactly like the
    in-memory host array."""
    ds, q = data
    path = str(tmp_path / "orig.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=ds.shape)
    mm[:] = ds
    mm.flush()
    src = tiered.memmap_source(path, dim=_D, hot_rows=64)
    d_dev, i_dev = ivf_pq.search_refined(sp, pq_index, q, _K,
                                         refine_ratio=4,
                                         dataset=jnp.asarray(ds))
    for _ in range(2):
        d_mm, i_mm = ivf_pq.search_refined(sp, pq_index, q, _K,
                                           refine_ratio=4, dataset=src)
        assert np.array_equal(np.asarray(d_dev), np.asarray(d_mm))
        assert np.array_equal(np.asarray(i_dev), np.asarray(i_mm))


def test_prefilter_composes_with_host_source(data, pq_index, sp):
    """Tombstone/user prefilters compose with the FIRST stage on the
    tiered path exactly as on the device path: filtered ids never
    surface, and the two paths agree bitwise."""
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.neighbors.common import BitsetFilter

    ds, q = data
    _, base = ivf_pq.search_refined(sp, pq_index, q, _K, refine_ratio=4,
                                    dataset=ds)
    drop = set(int(i) for i in np.asarray(base)[:, :3].ravel() if i >= 0)
    keep = np.ones(_N, bool)
    keep[list(drop)] = False
    filt = BitsetFilter(Bitset.from_dense(keep))
    d_dev, i_dev = ivf_pq.search_refined(sp, pq_index, q, _K,
                                         refine_ratio=4,
                                         dataset=jnp.asarray(ds),
                                         prefilter=filt)
    d_host, i_host = ivf_pq.search_refined(sp, pq_index, q, _K,
                                           refine_ratio=4, dataset=ds,
                                           prefilter=filt)
    assert np.array_equal(np.asarray(d_dev), np.asarray(d_host))
    assert np.array_equal(np.asarray(i_dev), np.asarray(i_host))
    got = set(int(i) for i in np.asarray(i_host).ravel() if i >= 0)
    assert not (got & drop)


# ---------------------------------------------------------------------------
# residency policy + accounting
# ---------------------------------------------------------------------------


def test_hot_cache_hit_rate_under_skew(data):
    """A Zipf-shaped repeated shortlist drives the steady-state HBM hit
    rate past 0.5 — the demand-driven residency the serve acceptance
    measures, at the library level."""
    ds, _ = data
    rng = np.random.default_rng(3)
    src = tiered.HostArraySource(ds, hot_rows=256, promote_after=2)
    q = jnp.zeros((8, _D), jnp.float32)
    hot_ids = rng.choice(_N, size=200, replace=False)
    for t in range(12):
        cand = rng.choice(hot_ids, size=(8, 16)).astype(np.int32)
        src.rerank(q, cand, 5, "sqeuclidean")
    st = src.stats()
    assert st["hit_rate_hbm"] > 0.5, st
    assert st["promotions"] > 0


def test_bytes_accounting_valid_and_deduped(data, pq_index, sp):
    """rerank.shortlist_rows counts VALID slots only (k*refine_ratio
    over-fetching past the candidate pool pads with -1 sentinels), and
    the host tier's bytes_fetched counts UNIQUE rows once the gather
    dedupes."""
    ds, q = data
    obs.set_mode("on")
    obs.reset()
    # n_probes=1 over 16 lists: ~125 candidates per query, so
    # k*refine_ratio = 10*32 = 320 over-fetches well past the pool
    sp1 = ivf_pq.SearchParams(n_probes=1)
    _, ids = ivf_pq.search_refined(sp1, pq_index, q, _K, refine_ratio=32,
                                   dataset=ds)
    snap = obs.snapshot()
    kc = ivf_pq.refined_shortlist_width(sp1, pq_index, _K, 32)
    m = q.shape[0]
    shortlist_rows = _value(snap, "rerank.shortlist_rows", algo="ivf_pq")
    assert shortlist_rows is not None
    # valid slots only — strictly fewer than the padded m*kc
    assert 0 < shortlist_rows < m * kc
    fetched = _value(snap, "rerank.bytes_fetched_total", source="host")
    row_bytes = _D * 4
    # deduped: unique rows <= valid slots, and a multiple of row_bytes
    assert fetched is not None and fetched % row_bytes == 0
    assert fetched / row_bytes <= shortlist_rows
    # the link counter records the padded pow2 upload (what actually
    # crossed), >= the deduped unique payload
    moved = _value(snap, "tiered.bytes_moved_total", link="host_to_device")
    assert moved is not None and moved >= fetched
    # (the >= 10x bytes-moved win vs the full upload is asserted at the
    # DEEP-smoke shape by scripts/deep100m.py --tiered-only, where the
    # dataset dwarfs the shortlist — at this unit-test scale they are
    # comparable by construction)


def test_cache_path_counts_valid_slots(data, sp):
    """The device-cache rerank path's accounting also drops sentinel
    padding slots (the ivf_pq.py:2604 fix)."""
    ds, _ = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0,
                                cache_decoded=True)
    index = ivf_pq.build(params, ds)
    assert index.cache_kind in ("i8", "i4")
    q = np.asarray(ds[:6])
    obs.set_mode("on")
    obs.reset()
    sp1 = ivf_pq.SearchParams(n_probes=1)
    ivf_pq.search_refined(sp1, index, q, _K, refine_ratio=32)
    snap = obs.snapshot()
    kc = ivf_pq.refined_shortlist_width(sp1, index, _K, 32)
    rows = _value(snap, "rerank.shortlist_rows", algo="ivf_pq")
    assert rows is not None and 0 < rows < q.shape[0] * kc


def test_back_compat_routing(data, pq_index, sp):
    """dataset= routing: host numpy fetches shortlist-only (tiered
    counters move), a device jax.Array keeps the full-upload fast path
    (no tiered counters)."""
    ds, q = data
    obs.set_mode("on")
    obs.reset()
    ivf_pq.search_refined(sp, pq_index, q, _K, refine_ratio=4,
                          dataset=jnp.asarray(ds))
    snap = obs.snapshot()
    assert _value(snap, "tiered.bytes_moved_total",
                  link="host_to_device") is None
    assert _value(snap, "rerank.bytes_fetched_total",
                  source="dataset") is not None
    obs.reset()
    ivf_pq.search_refined(sp, pq_index, q, _K, refine_ratio=4, dataset=ds)
    snap = obs.snapshot()
    assert _value(snap, "tiered.bytes_moved_total",
                  link="host_to_device") is not None
    assert _value(snap, "rerank.bytes_fetched_total",
                  source="host") is not None


def test_warm_covers_steady_state_rungs(data):
    """warm(m, c, k) traces every pow2 fetched-block rung, so live
    fetches of any unique-row count add zero traces."""
    ds, _ = data
    src = tiered.HostArraySource(ds, hot_rows=128)
    m, c, k = 8, 24, 5
    src.warm(m, c, k, "sqeuclidean")
    sizes = serve.trace_cache_sizes()
    before = (sizes["tiered._score_fetched_hot"],
              sizes["tiered._promote_scatter"])
    rng = np.random.default_rng(0)
    q = jnp.zeros((m, _D), jnp.float32)
    for t in range(6):
        # vary the unique-row mix (and thus the rung) batch to batch
        width = [1, 3, 40, 120, 190, 24][t]
        cand = rng.choice(_N, size=(m, c), replace=True)
        cand[:, width % c:] = -1
        src.rerank(q, cand.astype(np.int32), k, "sqeuclidean")
    sizes = serve.trace_cache_sizes()
    after = (sizes["tiered._score_fetched_hot"],
             sizes["tiered._promote_scatter"])
    assert after == before


# ---------------------------------------------------------------------------
# serve integration
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_data():
    rng = np.random.default_rng(23)
    ds = rng.standard_normal((1200, 24)).astype(np.float32)
    return ds


def _serve_params(**kw):
    base = dict(max_batch_rows=16, max_wait_ms=1.0, max_k=10)
    base.update(kw)
    return serve.ServeParams(**base)


def test_serve_tiered_bitwise_and_trace_stable(serve_data):
    """The serve adapter: tiered serving answers bitwise-identically to
    full-upload serving (tombstones composed), with zero post-warmup
    trace growth across a mixed-shape + mutating stream."""
    ds = serve_data
    bp = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                            kmeans_trainset_fraction=1.0)
    rng = np.random.default_rng(1)
    with serve.Server(_serve_params(tiered_rerank=True,
                                    tiered_hot_rows=128)) as srv, \
            serve.Server(_serve_params()) as ref:
        srv.create_index("v", ds, algo="ivf_pq", build_params=bp,
                         refine_ratio=3)
        ref.create_index("v", ds, algo="ivf_pq", build_params=bp,
                         refine_ratio=3)
        before = serve.trace_cache_sizes()
        for t in range(6):
            rows = [1, 3, 5, 2, 4, 1][t]
            k = [1, 5, 10, 7, 3, 10][t]
            q = rng.standard_normal((rows, 24)).astype(np.float32)
            d1, i1 = srv.search(q, k, index="v")
            d2, i2 = ref.search(q, k, index="v")
            assert np.array_equal(d1, d2)
            assert np.array_equal(i1, i2)
            if t == 3:
                srv.delete([int(i1[0, 0])], index="v")
                ref.delete([int(i2[0, 0])], index="v")
        assert serve.trace_cache_sizes() == before


def test_serve_result_cache_hits_and_invalidation(serve_data):
    """The result cache answers repeats without dispatch, and a
    delete (mutation epoch) or hot-swap (generation) invalidates."""
    ds = serve_data
    obs.set_mode("on")
    obs.reset()
    with serve.Server(_serve_params(result_cache_entries=32)) as srv:
        srv.create_index("v", ds, algo="brute_force")
        q = np.asarray(ds[5] + 0.01, np.float32)
        d1, i1 = srv.search(q, 5, index="v")
        d2, i2 = srv.search(q, 5, index="v")
        assert np.array_equal(d1, d2) and np.array_equal(i1, i2)
        snap = obs.snapshot()
        assert _value(snap, "serve.result_cache_hits_total",
                      index="v") == 1
        # mutation invalidates: the deleted id must drop out of the
        # repeat (a stale cache would keep serving it)
        victim = int(i1[0, 0])
        srv.delete([victim], index="v")
        d3, i3 = srv.search(q, 5, index="v")
        assert victim not in i3[0]
        # swap invalidates: new content (rows reversed => different
        # ids for the same query) served fresh
        srv.swap("v", dataset=ds[::-1].copy(), wait=True).result()
        d4, i4 = srv.search(q, 5, index="v")
        assert victim != int(i4[0, 0]) or not np.array_equal(i3, i4)
        snap = obs.snapshot()
        hits = _value(snap, "serve.result_cache_hits_total", index="v")
        assert hits == 1          # neither invalidated lookup hit


# ---------------------------------------------------------------------------
# memmap-backed streaming end-to-end (satellite 3)
# ---------------------------------------------------------------------------

_BN, _BD = 512, 16


def _memmap_dataset(tmp_path, name="stream.f32"):
    rng = np.random.default_rng(9)
    x = rng.standard_normal((_BN, _BD)).astype(np.float32)
    path = str(tmp_path / name)
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=x.shape)
    mm[:] = x
    mm.flush()
    return np.memmap(path, dtype=np.float32, mode="r", shape=x.shape)


def _batches_from_memmap(mm, bs=128):
    def make():
        for s in range(0, mm.shape[0], bs):
            yield jnp.asarray(np.asarray(mm[s:s + bs]))
    return make


def test_build_stream_from_memmap_kill_resume(tmp_path):
    """build_streamed over an np.memmap dataset, killed mid-pass-2 by
    the faultinject drill, resumes to a bitwise-identical index."""
    mm = _memmap_dataset(tmp_path)
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)
    base = ivf_pq.build_streamed(params, _batches_from_memmap(mm),
                                 _BN, _BD, trainset=np.asarray(mm))
    ckdir = str(tmp_path / "bck")
    with faultinject.inject("dead@stage:build.pass2#1"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            ivf_pq.build_streamed(params, _batches_from_memmap(mm),
                                  _BN, _BD, trainset=np.asarray(mm),
                                  checkpoint_dir=ckdir,
                                  checkpoint_every=1)
    got = ivf_pq.build_streamed(params, _batches_from_memmap(mm),
                                _BN, _BD, trainset=np.asarray(mm),
                                checkpoint_dir=ckdir, checkpoint_every=1,
                                resume=True)
    for f in ("codes", "indices", "list_sizes", "centers", "pq_centers"):
        assert np.array_equal(np.asarray(getattr(base, f)),
                              np.asarray(getattr(got, f))), f


class _TieredModule:
    """module.search adapter: search_refined over a persistent tiered
    host source (the stream.search_* plumbing shape)."""

    def __init__(self, src, refine_ratio=3):
        self.src = src
        self.refine_ratio = refine_ratio

    def search(self, sp, index, batch, k):
        return ivf_pq.search_refined(sp, index, batch, k,
                                     refine_ratio=self.refine_ratio,
                                     dataset=self.src)


def _write_fbin(path, arr):
    with open(path, "wb") as f:
        np.asarray(arr.shape, np.uint32).tofile(f)
        np.ascontiguousarray(arr, np.float32).tofile(f)


def test_search_file_tiered_kill_resume(tmp_path, data, pq_index, sp):
    """search_file streaming a query file through the TIERED rerank
    pipeline: dead@stage kill + checkpointed resume stays bitwise
    identical to the fault-free run."""
    ds, _ = data
    rng = np.random.default_rng(31)
    q = rng.standard_normal((200, _D)).astype(np.float32)
    qpath = str(tmp_path / "queries.fbin")
    _write_fbin(qpath, q)
    mod = _TieredModule(tiered.HostArraySource(ds, hot_rows=128))
    base_d, base_i = search_file(mod, sp, pq_index, qpath, _K,
                                 batch_rows=64)
    ckdir = str(tmp_path / "ck")
    with faultinject.inject("dead@chunk:2"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            search_file(mod, sp, pq_index, qpath, _K, batch_rows=64,
                        checkpoint_dir=ckdir, checkpoint_every=1,
                        retries=0)
    d, i = search_file(mod, sp, pq_index, qpath, _K, batch_rows=64,
                       checkpoint_dir=ckdir, resume=True)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


@pytest.mark.parametrize("chunk", [0, 2])
def test_tiered_oom_ladder_bitwise(tmp_path, data, pq_index, sp, chunk):
    """Injected OOM at a chunk boundary walks the halving ladder and
    converges to results bitwise-identical to the fault-free tiered
    run (rows are independent; the hot cache only changes WHERE bytes
    come from, never their values)."""
    ds, _ = data
    mm = ds  # host array source exercises the same path as memmap
    rng = np.random.default_rng(13)
    q = rng.standard_normal((192, _D)).astype(np.float32)
    mod = _TieredModule(tiered.HostArraySource(mm, hot_rows=64,
                                               promote_after=1))
    base_d, base_i = search_host_array(mod, sp, pq_index, q, _K,
                                       batch_rows=64)
    with faultinject.inject(f"oom@chunk:{chunk}"):
        d, i = search_host_array(mod, sp, pq_index, q, _K, batch_rows=64,
                                 backoff_s=0.001)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


def test_concurrent_rerank_stays_bitwise(data):
    """The promotion protocol under CONCURRENT rerank callers: slots
    are only reserved at plan time, the block snapshot rides the
    classify lock hold, the scatter is undonated, and the slot map
    learns promoted ids at a compare-and-swap commit — so interleaved
    threads can lose a promotion (a later re-fetch) but can never read
    a slot whose row isn't in their snapshot. A tiny hot cache with
    promote_after=1 maximizes eviction churn; every answer is checked
    against the single-threaded refine oracle."""
    import threading

    ds, _ = data
    src = tiered.HostArraySource(ds, hot_rows=64, promote_after=1,
                                 promote_batch=32)
    errs: list = []

    def worker(seed):
        r = np.random.default_rng(seed)
        try:
            for t in range(25):
                cand = r.integers(-1, _N, size=(4, 12)).astype(np.int32)
                q = r.standard_normal((4, _D)).astype(np.float32)
                da, ia = src.rerank(jnp.asarray(q), cand, 5,
                                    "sqeuclidean")
                db, ib = refine(ds, q, cand, 5)
                if not (np.array_equal(np.asarray(da), np.asarray(db))
                        and np.array_equal(np.asarray(ia),
                                           np.asarray(ib))):
                    errs.append(("mismatch", seed, t))
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errs.append(("raised", seed, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs[:3]
    st = src.stats()
    assert st["evictions"] > 0       # the churn regime was exercised


def test_fully_hot_batch_moves_zero_bytes(data):
    """Once a batch's whole shortlist is resident, the rerank uploads
    NOTHING — the miss-block operand comes from a cached device zeros
    block and bytes_moved stays flat."""
    ds, _ = data
    rng = np.random.default_rng(8)
    src = tiered.HostArraySource(ds, hot_rows=256, promote_after=1)
    q = jnp.zeros((4, _D), jnp.float32)
    cand = rng.choice(100, size=(4, 8)).astype(np.int32)
    src.rerank(q, cand, 5, "sqeuclidean")   # fetch + promote
    b1 = src.stats()["bytes_moved"]
    _, _, info = src.rerank_info(q, cand, 5, "sqeuclidean")
    assert info.hbm_hits == info.unique_rows and info.host_rows == 0
    assert info.bytes_link == 0
    assert src.stats()["bytes_moved"] == b1


# ---------------------------------------------------------------------------
# sharded composition
# ---------------------------------------------------------------------------


def test_sharded_rerank_source_composes(data, eight_device_mesh):
    """sharded_ivf_pq_search(rerank_source=): the merged first-stage
    shortlist reranked from host originals equals the manual
    composition, and partial_ok passes coverage through with a dead
    shard's rows invalid."""
    from raft_tpu.comms import sharded_ivf_pq_search

    rng = np.random.default_rng(41)
    n, d, k = 4096, 32, 10
    ds = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((12, d)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)
    index = ivf_pq.build(params, ds)
    spq = ivf_pq.SearchParams(n_probes=32)
    src = tiered.HostArraySource(ds, hot_rows=128)
    raw = sharded_ivf_pq_search(spq, index, q, k * 3, eight_device_mesh,
                                refine_ratio=1)
    d_man, i_man = refine(ds, q, np.asarray(raw[1]), k)
    d_int, i_int = sharded_ivf_pq_search(spq, index, q, k,
                                         eight_device_mesh,
                                         refine_ratio=3,
                                         rerank_source=src)
    assert np.array_equal(np.asarray(d_man), np.asarray(d_int))
    assert np.array_equal(np.asarray(i_man), np.asarray(i_int))
    with faultinject.inject("shard@rank:2"):
        d_p, i_p, cov = sharded_ivf_pq_search(
            spq, index, q, k, eight_device_mesh, refine_ratio=3,
            rerank_source=src, partial_ok=True)
    assert abs(float(np.asarray(cov)) - 7 / 8) < 1e-6
    assert np.asarray(d_p).shape == (12, k)


# ---------------------------------------------------------------------------
# source constructors / misc
# ---------------------------------------------------------------------------


def test_as_source_dispatch(data):
    ds, _ = data
    assert tiered.as_source(ds).kind == "host"
    assert tiered.as_source(jnp.asarray(ds)).kind == "device"
    src = tiered.HostArraySource(ds, hot_rows=4)
    assert tiered.as_source(src) is src
    with pytest.raises(TypeError):
        tiered.HostArraySource(jnp.asarray(ds))


def test_memmap_source_fbin_header(tmp_path, data):
    ds, _ = data
    path = str(tmp_path / "ds.fbin")
    _write_fbin(path, ds)
    src = tiered.memmap_source(path)
    assert src.rows == _N and src.dim == _D
    assert np.array_equal(np.asarray(src.dataset[3]), ds[3])


def test_hot_rows_budget_knob(data):
    """hot_rows=None draws the capacity from tuning.budget — the
    cache-budget knob (a record_budget ceiling clamps it)."""
    ds, _ = data
    tuning.record_budget(tiered.HOT_ROWS_BUDGET, 32)
    src = tiered.HostArraySource(ds)
    assert src.hot_capacity == 32
