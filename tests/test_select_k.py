"""select_k vs numpy sort — analog of cpp/test/matrix select_k suites which
cross-check every algo against a reference implementation."""

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.matrix import select_k
from raft_tpu.matrix.select_k import select_k_threshold


@pytest.mark.parametrize("batch,n,k", [(1, 100, 5), (16, 1000, 32), (4, 257, 256), (3, 4096, 1000)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(rng, batch, n, k, select_min):
    x = rng.standard_normal((batch, n)).astype(np.float32)
    vals, idxs = select_k(x, k, select_min=select_min)
    vals, idxs = np.asarray(vals), np.asarray(idxs)
    order = np.argsort(x if select_min else -x, axis=1)[:, :k]
    want = np.take_along_axis(x, order, axis=1)
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-6)
    # indices must point at the right values
    np.testing.assert_allclose(np.take_along_axis(x, idxs, axis=1), vals)


def test_select_k_with_in_idx(rng):
    x = rng.standard_normal((4, 50)).astype(np.float32)
    src = rng.integers(0, 10_000, (4, 50)).astype(np.int32)
    vals, idxs = select_k(x, 7, in_idx=src)
    idxs = np.asarray(idxs)
    # every returned index must come from the source-index map
    for b in range(4):
        assert set(idxs[b].tolist()) <= set(src[b].tolist())


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_integer_exact_above_2p24(select_min):
    """ADVICE r5 finding 1: the integer select-min path used to cast to
    f32 before top_k, collapsing adjacent values above 2^24 (2^24+1
    rounds onto 2^24). The integer-domain bitwise-NOT mapping is exact
    everywhere, including INT32_MIN (whose two's-complement negation
    overflows)."""
    base = 1 << 24
    x = np.array(
        [[base + 3, base + 1, base + 2, base, -base - 1, -base - 2,
          -(2**31), 2**31 - 1, 0]], np.int32,
    )
    k = 4
    v, i = select_k(jnp.asarray(x), k, select_min=select_min)
    v, i = np.asarray(v), np.asarray(i)
    srt = np.sort(x, axis=1)
    want = srt[:, :k] if select_min else srt[:, ::-1][:, :k]
    np.testing.assert_array_equal(v, want)
    np.testing.assert_array_equal(np.take_along_axis(x, i, axis=1), v)
    assert v.dtype == x.dtype


def test_select_k_unsigned_min():
    """Unsigned select-min through the same bitwise-NOT order reversal
    (~x = UINT_MAX - x): exact at full-range values."""
    x = np.array([[2**32 - 1, (1 << 24) + 1, (1 << 24) + 2, 7, 0]],
                 np.uint32)
    v, i = select_k(jnp.asarray(x), 3, select_min=True)
    np.testing.assert_array_equal(np.asarray(v), [[0, 7, (1 << 24) + 1]])
    assert np.asarray(v).dtype == x.dtype


def test_select_k_1d(rng):
    x = rng.standard_normal(64).astype(np.float32)
    vals, idxs = select_k(x, 4)
    assert vals.shape == (4,)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x)[:4], rtol=1e-6)


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_threshold_path(rng, select_min):
    x = rng.standard_normal((4, 8192)).astype(np.float32)
    k = 500
    vals, idxs = select_k_threshold(x, k, select_min=select_min)
    vals = np.asarray(vals)
    want = np.sort(x, axis=1)
    want = want[:, :k] if select_min else want[:, ::-1][:, :k]
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-5)


def test_tournament_topk_exact():
    """Large-k tournament select (the compacting radix-select analog,
    select_radix.cuh:231,546) is EXACT: matches numpy argsort for
    k in {300, 1024} at n >> k, min and max, with correct ids."""
    from raft_tpu.matrix.select_k import _tournament_topk, select_k

    rng = np.random.default_rng(5)
    m, n = 4, 16384
    x = rng.standard_normal((m, n)).astype(np.float32)
    for k in (300, 1024):
        for select_min in (True, False):
            v, i = _tournament_topk(jnp.asarray(x), k, select_min)
            v, i = np.asarray(v), np.asarray(i)
            order = np.argsort(x if select_min else -x, axis=1)[:, :k]
            want_v = np.take_along_axis(x, order, axis=1)
            np.testing.assert_allclose(v, want_v, rtol=0, atol=0)
            got_v_from_ids = np.take_along_axis(x, i, axis=1)
            np.testing.assert_allclose(got_v_from_ids, v)
    # dispatch routes large k through the tournament
    v, i = select_k(x, 1024)
    np.testing.assert_allclose(
        np.asarray(v), np.sort(x, axis=1)[:, :1024])


def test_tournament_topk_non_pow2_n():
    from raft_tpu.matrix.select_k import _tournament_topk

    rng = np.random.default_rng(6)
    x = rng.standard_normal((3, 10_001)).astype(np.float32)
    v, i = _tournament_topk(jnp.asarray(x), 512, True)
    np.testing.assert_allclose(np.asarray(v), np.sort(x, axis=1)[:, :512])
    assert (np.asarray(i) >= 0).all()


def test_merge_topk_routes_large_k_through_tournament(monkeypatch):
    """VERDICT r4 #5: the large-k dispatch must be reachable from a real
    library path — brute_force.knn's exact merge at k=512 over 8k rows
    lands in _tournament_topk (the radix-select-analog regime,
    select_radix.cuh:231), with ids agreeing with the numpy oracle.
    Runs with RAFT_TPU_TUNING=off: this pins the ANALYTIC projection's
    routing (the measured CPU table legitimately prefers top_k — the
    whole point of measuring)."""
    import importlib

    from raft_tpu import tuning

    sk = importlib.import_module("raft_tpu.matrix.select_k")
    from raft_tpu.neighbors import brute_force

    monkeypatch.setattr(tuning, "_mode_override", "off")

    calls = []
    orig = sk._tournament_topk

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(sk, "_tournament_topk", spy)
    rng = np.random.default_rng(11)
    x = rng.standard_normal((8192, 16)).astype(np.float32)
    q = rng.standard_normal((4, 16)).astype(np.float32)
    d, i = brute_force.knn(q, x, 512)
    assert calls, "k=512 exact merge did not reach the tournament"
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.sort(d2, axis=1)[:, :512]
    np.testing.assert_allclose(np.asarray(d), want, rtol=1e-4, atol=1e-4)
    got = np.take_along_axis(d2, np.asarray(i), axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# edge cases across all three dispatch rungs (ISSUE 9 satellite): the
# hierarchical rung must honor every contract the top_k arm set
# ---------------------------------------------------------------------------

_IMPLS = ("top_k", "tournament", "hierarchical")


def test_select_k_k_out_of_range(rng):
    x = rng.standard_normal((2, 64)).astype(np.float32)
    for bad_k in (0, -1, 65):
        with pytest.raises(ValueError, match="out of range"):
            select_k(x, bad_k)


@pytest.mark.parametrize("impl", _IMPLS)
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_k_equals_n(rng, impl, select_min):
    """k == n: every rung returns the full row, sorted best-first."""
    x = rng.standard_normal((3, 96)).astype(np.float32)
    v, i = select_k(x, 96, select_min=select_min, impl=impl)
    want = np.sort(x, axis=1)
    want = want if select_min else want[:, ::-1]
    np.testing.assert_array_equal(np.asarray(v), want)
    np.testing.assert_array_equal(
        np.take_along_axis(x, np.asarray(i), axis=1), want)


@pytest.mark.parametrize("impl", _IMPLS)
def test_select_k_all_equal_ties_stable(impl):
    """All-equal rows: ids come back as 0..k-1 in order (stable tie
    break) on every rung — the compare-exchange networks must not swap
    on equal keys and the merges must prefer the earlier block."""
    x = np.zeros((2, 4096), np.float32)
    v, i = select_k(x, 100, impl=impl)
    np.testing.assert_array_equal(
        np.asarray(i),
        np.broadcast_to(np.arange(100, dtype=np.int32), (2, 100)))
    assert (np.asarray(v) == 0).all()


@pytest.mark.parametrize("impl", _IMPLS)
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_inf_rows(rng, impl, select_min):
    """±inf entries are real candidates: best-infinity first, worst
    last, real column ids kept (the sentinel-masking convention every
    scan path relies on)."""
    x = rng.standard_normal((2, 2048)).astype(np.float32)
    x[:, 7] = -np.inf
    x[:, 13] = np.inf
    v, i = select_k(x, 2048, select_min=select_min, impl=impl)
    v, i = np.asarray(v), np.asarray(i)
    best, worst = (-np.inf, np.inf) if select_min else (np.inf, -np.inf)
    best_col, worst_col = (7, 13) if select_min else (13, 7)
    assert v[:, 0].tolist() == [best, best]
    assert i[:, 0].tolist() == [best_col, best_col]
    assert v[:, -1].tolist() == [worst, worst]
    assert i[:, -1].tolist() == [worst_col, worst_col]


@pytest.mark.parametrize("impl", ["top_k", "hierarchical"])
def test_select_k_nan_rows_quarantined(rng, impl):
    """NaN entries on the NaN-tolerant rungs (top_k, hierarchical —
    the tournament documents NaN as unsupported): never selected before
    a finite value, reported as NaN with their real column id."""
    x = rng.standard_normal((2, 1024)).astype(np.float32)
    x[:, 5] = np.nan
    v, i = select_k(x, 1024, impl=impl)
    v, i = np.asarray(v), np.asarray(i)
    # every finite value precedes the NaN slot
    nan_pos = np.argmax(np.isnan(v), axis=1)
    assert (nan_pos >= 1023 - 1).all()        # last or tied with +inf
    assert (i[np.isnan(v)] == 5).all()
    # the finite prefix is exactly the sorted finite values
    np.testing.assert_array_equal(
        v[0, :1023], np.sort(x[0][~np.isnan(x[0])]))


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_integer_hierarchical_exact_above_2p24(select_min):
    """The PR-1 integer-domain contract survives the hierarchical rung:
    values adjacent above 2^24 (and INT32_MIN) select exactly, in the
    input dtype — the rung's keys and payloads never leave the integer
    domain."""
    base = 1 << 24
    x = np.array(
        [[base + 3, base + 1, base + 2, base, -base - 1, -base - 2,
          -(2**31), 2**31 - 1, 0]], np.int32,
    )
    k = 4
    v, i = select_k(jnp.asarray(x), k, select_min=select_min,
                    impl="hierarchical")
    v, i = np.asarray(v), np.asarray(i)
    srt = np.sort(x, axis=1)
    want = srt[:, :k] if select_min else srt[:, ::-1][:, :k]
    np.testing.assert_array_equal(v, want)
    np.testing.assert_array_equal(np.take_along_axis(x, i, axis=1), v)
    assert v.dtype == x.dtype


def test_select_k_unsigned_and_bool_hierarchical():
    xu = np.array([[2**32 - 1, (1 << 24) + 1, (1 << 24) + 2, 7, 0]],
                  np.uint32)
    v, i = select_k(jnp.asarray(xu), 3, select_min=True,
                    impl="hierarchical")
    np.testing.assert_array_equal(np.asarray(v), [[0, 7, (1 << 24) + 1]])
    assert np.asarray(v).dtype == xu.dtype
    xb = np.array([[True, False, True, False]])
    v, i = select_k(jnp.asarray(xb), 2, select_min=True,
                    impl="hierarchical")
    assert not np.asarray(v).any()


def test_select_k_tournament_rejects_integers_still():
    """The float-only guard on the tournament must survive the new
    dispatch candidates (integers route to top_k/hierarchical)."""
    x = np.arange(64, dtype=np.int32)[None]
    with pytest.raises(ValueError, match="float-only"):
        select_k(x, 4, impl="tournament")


def test_dispatch_candidates_include_hierarchical(monkeypatch):
    """dispatch_select_impl offers the hierarchical rung wherever the
    tree has >= 4 tiles (floats AND integers), and the analytic
    fallback routes large-k INTEGER selects — which the float-only
    tournament cannot take — onto it."""
    from raft_tpu import tuning
    from raft_tpu.matrix.select_k import dispatch_select_impl

    monkeypatch.setattr(tuning, "_mode_override", "off")
    impl = dispatch_select_impl(4, 65536, 1024, np.dtype(np.int32))
    assert impl == "hierarchical"
    # float large-k keeps its measured/projected tournament route
    impl = dispatch_select_impl(4, 65536, 1024, np.dtype(np.float32))
    assert impl == "tournament"


def test_select_k_in_idx_pad_slots_never_wrap():
    """Tournament pad slots (structural -1 positions from the
    power-of-two padding) must map to -1 through an in_idx mapping — an
    unmasked take_along_axis would WRAP to in_idx[..., -1] and return
    that row's last id once per selected pad slot. Detector: with only
    100 finite entries and k=512, hundreds of pad slots reach the
    output; the last column's distinctive id may appear at most once
    (itself), so any repeat is the wrap artifact. In-data inf entries
    legitimately keep their real ids (same as lax.top_k)."""
    from raft_tpu.matrix.select_k import select_k

    rng = np.random.default_rng(12)
    n, k = 5000, 512          # pads to 8*1024: 3192 structural pad slots
    x = np.full((2, n), np.inf, np.float32)
    x[:, :100] = rng.standard_normal((2, 100)).astype(np.float32)
    ids = np.broadcast_to(np.arange(n, dtype=np.int32) + 1000, (2, n))
    v, i = select_k(jnp.asarray(x), k, in_idx=jnp.asarray(ids))
    i = np.asarray(i)
    assert (i[:, :100] >= 1000).all()
    last_id = 1000 + n - 1
    assert (i == last_id).sum(axis=1).max() <= 1
    # every emitted id is -1 or a real mapped id
    assert (((i == -1) | (i >= 1000)) & (i <= last_id)).all()
