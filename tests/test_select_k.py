"""select_k vs numpy sort — analog of cpp/test/matrix select_k suites which
cross-check every algo against a reference implementation."""

import numpy as np
import pytest

from raft_tpu.matrix import select_k
from raft_tpu.matrix.select_k import select_k_threshold


@pytest.mark.parametrize("batch,n,k", [(1, 100, 5), (16, 1000, 32), (4, 257, 256), (3, 4096, 1000)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(rng, batch, n, k, select_min):
    x = rng.standard_normal((batch, n)).astype(np.float32)
    vals, idxs = select_k(x, k, select_min=select_min)
    vals, idxs = np.asarray(vals), np.asarray(idxs)
    order = np.argsort(x if select_min else -x, axis=1)[:, :k]
    want = np.take_along_axis(x, order, axis=1)
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-6)
    # indices must point at the right values
    np.testing.assert_allclose(np.take_along_axis(x, idxs, axis=1), vals)


def test_select_k_with_in_idx(rng):
    x = rng.standard_normal((4, 50)).astype(np.float32)
    src = rng.integers(0, 10_000, (4, 50)).astype(np.int32)
    vals, idxs = select_k(x, 7, in_idx=src)
    idxs = np.asarray(idxs)
    # every returned index must come from the source-index map
    for b in range(4):
        assert set(idxs[b].tolist()) <= set(src[b].tolist())


def test_select_k_1d(rng):
    x = rng.standard_normal(64).astype(np.float32)
    vals, idxs = select_k(x, 4)
    assert vals.shape == (4,)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x)[:4], rtol=1e-6)


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_threshold_path(rng, select_min):
    x = rng.standard_normal((4, 8192)).astype(np.float32)
    k = 500
    vals, idxs = select_k_threshold(x, k, select_min=select_min)
    vals = np.asarray(vals)
    want = np.sort(x, axis=1)
    want = want[:, :k] if select_min else want[:, ::-1][:, :k]
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-5)
