"""select_k vs numpy sort — analog of cpp/test/matrix select_k suites which
cross-check every algo against a reference implementation."""

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.matrix import select_k
from raft_tpu.matrix.select_k import select_k_threshold


@pytest.mark.parametrize("batch,n,k", [(1, 100, 5), (16, 1000, 32), (4, 257, 256), (3, 4096, 1000)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(rng, batch, n, k, select_min):
    x = rng.standard_normal((batch, n)).astype(np.float32)
    vals, idxs = select_k(x, k, select_min=select_min)
    vals, idxs = np.asarray(vals), np.asarray(idxs)
    order = np.argsort(x if select_min else -x, axis=1)[:, :k]
    want = np.take_along_axis(x, order, axis=1)
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-6)
    # indices must point at the right values
    np.testing.assert_allclose(np.take_along_axis(x, idxs, axis=1), vals)


def test_select_k_with_in_idx(rng):
    x = rng.standard_normal((4, 50)).astype(np.float32)
    src = rng.integers(0, 10_000, (4, 50)).astype(np.int32)
    vals, idxs = select_k(x, 7, in_idx=src)
    idxs = np.asarray(idxs)
    # every returned index must come from the source-index map
    for b in range(4):
        assert set(idxs[b].tolist()) <= set(src[b].tolist())


def test_select_k_1d(rng):
    x = rng.standard_normal(64).astype(np.float32)
    vals, idxs = select_k(x, 4)
    assert vals.shape == (4,)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x)[:4], rtol=1e-6)


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_threshold_path(rng, select_min):
    x = rng.standard_normal((4, 8192)).astype(np.float32)
    k = 500
    vals, idxs = select_k_threshold(x, k, select_min=select_min)
    vals = np.asarray(vals)
    want = np.sort(x, axis=1)
    want = want[:, :k] if select_min else want[:, ::-1][:, :k]
    np.testing.assert_allclose(np.sort(vals, axis=1), np.sort(want, axis=1), rtol=1e-5)


def test_tournament_topk_exact():
    """Large-k tournament select (the compacting radix-select analog,
    select_radix.cuh:231,546) is EXACT: matches numpy argsort for
    k in {300, 1024} at n >> k, min and max, with correct ids."""
    from raft_tpu.matrix.select_k import _tournament_topk, select_k

    rng = np.random.default_rng(5)
    m, n = 4, 16384
    x = rng.standard_normal((m, n)).astype(np.float32)
    for k in (300, 1024):
        for select_min in (True, False):
            v, i = _tournament_topk(jnp.asarray(x), k, select_min)
            v, i = np.asarray(v), np.asarray(i)
            order = np.argsort(x if select_min else -x, axis=1)[:, :k]
            want_v = np.take_along_axis(x, order, axis=1)
            np.testing.assert_allclose(v, want_v, rtol=0, atol=0)
            got_v_from_ids = np.take_along_axis(x, i, axis=1)
            np.testing.assert_allclose(got_v_from_ids, v)
    # dispatch routes large k through the tournament
    v, i = select_k(x, 1024)
    np.testing.assert_allclose(
        np.asarray(v), np.sort(x, axis=1)[:, :1024])


def test_tournament_topk_non_pow2_n():
    from raft_tpu.matrix.select_k import _tournament_topk

    rng = np.random.default_rng(6)
    x = rng.standard_normal((3, 10_001)).astype(np.float32)
    v, i = _tournament_topk(jnp.asarray(x), 512, True)
    np.testing.assert_allclose(np.asarray(v), np.sort(x, axis=1)[:, :512])
    assert (np.asarray(i) >= 0).all()
