"""CAGRA tests — reference pattern (cpp/test/neighbors/ann_cagra.cuh):
recall vs exact oracle, graph-optimize semantics vs a naive oracle of the
reference's detour-count rule, serialization round-trips."""

import numpy as np
import pytest

from raft_tpu.neighbors import cagra
from tests.oracles import eval_recall, naive_knn


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    centers = rng.uniform(-5, 5, (32, 24)).astype(np.float32)
    x = (centers[rng.integers(0, 32, 10_000)]
         + 0.7 * rng.standard_normal((10_000, 24))).astype(np.float32)
    q = (centers[rng.integers(0, 32, 200)]
         + 0.7 * rng.standard_normal((200, 24))).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def index(dataset):
    x, _ = dataset
    params = cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24
    )
    return cagra.build(params, x)


def test_build_structure(dataset, index):
    x, _ = dataset
    n = x.shape[0]
    assert index.graph.shape == (n, 24)
    g = np.asarray(index.graph)
    assert g.min() >= 0 and g.max() < n
    # no self-edges
    assert not (g == np.arange(n)[:, None]).any()


def test_search_recall(dataset, index):
    x, q = dataset
    k = 10
    sp = cagra.SearchParams(itopk_size=64, search_width=2)
    dist, idx = cagra.search(sp, index, q, k)
    _, want = naive_knn(q, x, k)
    rec = eval_recall(np.asarray(idx), want)
    assert rec > 0.9, rec


def test_search_distances_are_exactish(dataset, index):
    x, q = dataset
    k = 5
    sp = cagra.SearchParams(itopk_size=64, search_width=2)
    dist, idx = cagra.search(sp, index, q[:20], k)
    idx = np.asarray(idx)
    dist = np.asarray(dist)
    for i in range(20):
        for j in range(k):
            if idx[i, j] < 0:
                continue
            true = ((q[i] - x[idx[i, j]]) ** 2).sum()
            np.testing.assert_allclose(dist[i, j], true, rtol=5e-2, atol=0.5)


def _naive_detour_counts(graph):
    """Reference rule (graph_core.cuh:360 comment): for edge A->B at rank
    kAB, count ranks kAD < kAB with B in graph[A[kAD]]."""
    n, D = graph.shape
    out = np.zeros((n, D), np.int32)
    for a in range(n):
        for kab in range(D):
            b = graph[a, kab]
            c = 0
            for kad in range(kab):
                if b in graph[graph[a, kad]]:
                    c += 1
            out[a, kab] = c
    return out


def test_detour_counts_match_oracle():
    rng = np.random.default_rng(5)
    n, D = 40, 6
    graph = np.stack(
        [rng.choice([j for j in range(n) if j != i], D, replace=False)
         for i in range(n)]
    ).astype(np.int32)
    got = np.asarray(cagra._detour_counts(graph, 16))
    want = _naive_detour_counts(graph)
    np.testing.assert_array_equal(got, want)


def test_optimize_degree_and_reverse_edges():
    rng = np.random.default_rng(6)
    n, D, deg = 60, 12, 6
    graph = np.stack(
        [rng.choice([j for j in range(n) if j != i], D, replace=False)
         for i in range(n)]
    ).astype(np.int32)
    out = np.asarray(cagra.optimize(graph, deg, chunk=16))
    assert out.shape == (n, deg)
    assert (out >= 0).all() and (out < n).all()
    # rows contain no duplicate edges
    for i in range(n):
        assert len(set(out[i])) == deg
    # protected prefix preserved: first deg//2 = lowest-detour originals
    counts = _naive_detour_counts(graph)
    for i in range(5):
        key = counts[i] * D + np.arange(D)
        keep = graph[i][np.argsort(key, kind="stable")][: deg // 2]
        np.testing.assert_array_equal(out[i, : deg // 2], keep)


def test_from_graph_and_serialize(dataset, index, tmp_path):
    x, q = dataset
    p = str(tmp_path / "cagra.idx")
    cagra.save(p, index)
    loaded = cagra.load(p)
    np.testing.assert_array_equal(
        np.asarray(loaded.graph), np.asarray(index.graph)
    )
    sp = cagra.SearchParams(itopk_size=32)
    _, i1 = cagra.search(sp, index, q[:10], 5)
    _, i2 = cagra.search(sp, loaded, q[:10], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_hnswlib_export(index, tmp_path):
    import struct

    p = str(tmp_path / "cagra_hnsw.bin")
    cagra.serialize_to_hnswlib(p, index)
    n, dim, deg = index.size, index.dim, index.graph_degree
    size_links0 = deg * 4 + 4
    size_per_elem = size_links0 + dim * 4 + 8
    with open(p, "rb") as f:
        # parse exactly as hnswlib HierarchicalNSW::loadIndex does
        header = f.read(6 * 8 + 4 + 4 + 3 * 8 + 8 + 8)
        offset0, maxn, cur, spe, label_off, off_data = struct.unpack(
            "<6Q", header[:48]
        )
        maxlevel, entry = struct.unpack("<iI", header[48:56])
        maxM, maxM0, M = struct.unpack("<3Q", header[56:80])
        (mult,) = struct.unpack("<d", header[80:88])
        (efc,) = struct.unpack("<Q", header[88:96])
        assert offset0 == 0
        assert (maxn, cur) == (n, n)
        assert spe == size_per_elem
        assert off_data == size_links0
        assert label_off == size_links0 + dim * 4
        assert maxlevel == 0 and entry == 0
        assert maxM0 == deg and maxM == M == deg // 2
        assert mult > 0 and efc > 0
        # first element: link count (unsigned short in the first 2 bytes,
        # like hnswlib getListCount), then the graph row, data, label
        first = f.read(size_per_elem)
        cnt = struct.unpack("<H", first[:2])[0]
        assert cnt == deg
        row = np.frombuffer(first[4 : 4 + deg * 4], dtype="<u4")
        np.testing.assert_array_equal(row, np.asarray(index.graph[0]))
        vec = np.frombuffer(first[off_data : off_data + dim * 4], "<f4")
        np.testing.assert_allclose(vec, np.asarray(index.dataset[0]),
                                   rtol=1e-6)
        (label0,) = struct.unpack("<Q", first[label_off : label_off + 8])
        assert label0 == 0
        # level list sizes: one zero int per element
        f.seek(0, 2)
        end = f.tell()
        assert end == 96 + n * size_per_elem + n * 4


def test_prefilter(dataset, index):
    """Bitset prefilter restricts results to allowed ids on both beam
    paths (reference cagra::search_with_filtering, cagra.cuh:373-404)."""
    from raft_tpu.core.bitset import Bitset

    x, q = dataset
    n = x.shape[0]
    k = 5
    allowed = np.zeros(n, bool)
    allowed[: n // 2] = True
    bits = Bitset.from_dense(allowed)
    for impl in ("xla", "pallas_interpret"):
        sp = cagra.SearchParams(itopk_size=96, n_seeds=128, scan_impl=impl)
        _, idx = cagra.search(sp, index, q, k, prefilter=bits)
        idx = np.asarray(idx)
        assert ((idx == -1) | (idx < n // 2)).all(), impl
        _, want = naive_knn(q, x[: n // 2], k)
        assert eval_recall(idx, want) > 0.8, impl


def test_prefilter_fewer_than_k_valid(dataset, index):
    from raft_tpu.core.bitset import Bitset

    x, q = dataset
    n = x.shape[0]
    k = 10
    allowed = np.zeros(n, bool)
    allowed[:3] = True                      # only 3 candidates exist
    bits = Bitset.from_dense(allowed)
    sp = cagra.SearchParams(itopk_size=64, n_seeds=256, max_iterations=30)
    _, idx = cagra.search(sp, index, q, k, prefilter=bits)
    idx = np.asarray(idx)
    assert ((idx == -1) | (idx < 3)).all()


def test_hnswlib_export_independent_reader(dataset, index, tmp_path):
    """Round-trip through the independent header-driven hnswlib reader
    (raft_tpu.neighbors.hnswlib_io — parses via the file's OWN header
    offsets, so writer-layout bugs fail asymmetrically) and prove the
    exported graph is navigable with hnswlib's own search algorithm."""
    from raft_tpu.neighbors.hnswlib_io import load_hnswlib_index, greedy_search
    from tests.oracles import naive_knn

    x, q = dataset
    p = str(tmp_path / "cagra_hnsw2.bin")
    cagra.serialize_to_hnswlib(p, index)
    loaded = load_hnswlib_index(p, dim=x.shape[1])
    np.testing.assert_allclose(loaded.data, x, rtol=1e-6)
    np.testing.assert_array_equal(loaded.labels, np.arange(x.shape[0]))
    # every CAGRA edge present as a level-0 link
    np.testing.assert_array_equal(loaded.links, np.asarray(index.graph))

    # navigability: greedy base-layer search (hnswlib's algorithm) on a
    # SINGLE-component dataset. (On multi-cluster data the CAGRA graph
    # legitimately splits into per-cluster components; the single-entry
    # base-layer walk can't cross them — the same envelope the
    # reference's base-layer-only export has. Our own beam search covers
    # that case with its random seed slab.)
    rng = np.random.default_rng(7)
    xs = rng.standard_normal((2000, 16)).astype(np.float32)
    qs = rng.standard_normal((30, 16)).astype(np.float32)
    sidx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16), xs)
    p2 = str(tmp_path / "cagra_hnsw3.bin")
    cagra.serialize_to_hnswlib(p2, sidx)
    sld = load_hnswlib_index(p2, dim=16)
    k = 5
    _, want = naive_knn(qs, xs, k)
    hits = 0
    for i in range(30):
        _, ids = greedy_search(sld, qs[i], k, ef=96)
        hits += len(set(ids.tolist()) & set(want[i].tolist()))
    assert hits / (30 * k) > 0.8


@pytest.mark.parametrize("density", [0.5, 0.9])
def test_prefilter_dense_recall(dataset, index, density):
    """In-traversal filtering (reference expel-after-expand,
    search_single_cta_kernel-inl.cuh:725-772): recall vs a filtered
    brute-force oracle stays high even when most of the dataset is
    filtered out — round 3 filtered only at extraction and collapsed
    under dense filters."""
    from raft_tpu.core.bitset import Bitset

    x, q = dataset
    n, k = x.shape[0], 10
    rng2 = np.random.default_rng(int(density * 10))
    allowed = rng2.random(n) >= density        # keep 1-density of rows
    bits = Bitset.from_dense(allowed)
    ids = np.flatnonzero(allowed)
    _, wloc = naive_knn(q, x[allowed], k)
    want = ids[wloc]
    itopk = 128 if density <= 0.5 else 256
    for impl in ("xla", "pallas_interpret"):
        sp = cagra.SearchParams(itopk_size=itopk, search_width=4,
                                max_iterations=40, n_seeds=512,
                                scan_impl=impl)
        _, idx = cagra.search(sp, index, q, k, prefilter=bits)
        idx = np.asarray(idx)
        assert ((idx == -1) | allowed[np.maximum(idx, 0)]).all(), impl
        rec = eval_recall(idx, want)
        assert rec > 0.98 - 0.02, (impl, density, rec)
