"""graft-scope observability tests (ISSUE 4, marker ``obs``).

Covers: span nesting on one thread and ACROSS threads, metric
registry semantics (counter/gauge/histogram bucket edges, label
keying), Prometheus exposition round-trip, flight-recorder dump on an
injected ``dead@stage:search`` fault, the resilience/tuning wiring
(retries, OOM-ladder downshifts, checkpoint counters, dispatch
counts), the GL007 recompile hook, thread-local legacy trace ranges,
an off-path overhead guard, and the ISSUE acceptance run (ivf_pq
build+search under ``oom@chunk`` + sharded coverage)."""

import json
import os
import re
import threading
import tracemalloc

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import obs, resilience, tuning
from raft_tpu.obs import federation as obs_federation
from raft_tpu.obs import flight as obs_flight
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.obs import trace as obs_trace
from raft_tpu.resilience import faultinject

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_OBS", raising=False)
    monkeypatch.delenv("RAFT_TPU_OBS_DIR", raising=False)
    obs.set_mode(None)
    obs.reset()
    faultinject.clear()
    yield
    obs.reset()
    obs.set_mode(None)
    faultinject.clear()
    tuning.reload()          # drop OOM-survivor budgets learned in a test


def _value(snap, name, /, **labels):
    """The value of the (name, labels) series in a snapshot, or None."""
    want = {str(k): str(v) for k, v in labels.items()}
    for p in snap["metrics"].get(name, {}).get("points", []):
        if all(p["labels"].get(k) == v for k, v in want.items()):
            return p.get("value", p)
    return None


# ---------------------------------------------------------------------------
# modes + off-path overhead
# ---------------------------------------------------------------------------


def test_default_mode_off():
    assert obs.mode() == "off"
    assert not obs.enabled()


def test_set_mode_validates():
    with pytest.raises(ValueError):
        obs.set_mode("loud")


def test_env_mode_via_reload(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_OBS", "flight")
    obs.reload()
    assert obs.mode() == "flight" and obs.enabled()
    monkeypatch.setenv("RAFT_TPU_OBS", "nonsense")
    obs.reload()
    assert obs.mode() == "off"


def test_off_path_is_shared_singleton_and_registry_silent():
    assert obs.span("a", x=1) is obs.span("b")
    assert obs.entry_span("search", "x", queries=4) is obs.span("c")
    obs.counter("nope", 3, algo="x")
    obs.gauge("nope_g", 1.0)
    obs.observe("nope_h", 2.0)
    obs.event("nope_e")
    with obs.span("quiet") as sp:
        sp.set(a=1).sync(None)
    # graft-trace off contract (ISSUE 13): no ids minted, payloads hand
    # back UNCHANGED (identity, not a copy), stages/finishes silent
    assert obs.start_trace("e") is None
    p = {"q": 1}
    assert obs.traced_payload(p) is p
    obs.trace.stage(None, "rpc", ms=1.0)
    assert obs.trace.finish(None) is None
    assert obs.trace.current() is None
    assert obs.trace_report() == []
    assert obs.snapshot(runtime_gauges=False)["metrics"] == {}
    assert obs.recent() == []
    assert obs.flight_events() == []


def test_off_path_retains_no_allocations():
    # warm every code path first so lazy init cannot count as growth
    obs.counter("warm")
    with obs.span("warm"):
        pass
    obs_dir = os.path.dirname(obs.__file__)
    tracemalloc.start()
    try:
        base = tracemalloc.take_snapshot()
        payload = {"q": 1}
        for _ in range(500):
            obs.counter("x", 1, algo="y")
            obs.observe("h", 1.0, stage="s")
            with obs.span("s", a=1) as sp:
                sp.set(b=2)
            # graft-trace joins the off-path contract (ISSUE 13)
            obs.start_trace("e", k=4)
            obs.traced_payload(payload)
            obs.trace.stage(None, "rpc", ms=1.0)
            obs.trace.finish(None)
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    retained = sum(
        st.size_diff
        for st in after.compare_to(base, "filename")
        if st.traceback and st.traceback[0].filename.startswith(obs_dir)
    )
    # the enabled-check must be the whole story: a real off-path leak
    # (a Span/point/waterfall per call surviving into a registry, tree,
    # or ring) retains tens of KB over 3500 calls; the 2 KB tolerance
    # absorbs tracemalloc's cross-thread/freelist attribution noise
    # under the full suite (the r13 trace calls grew the loop from 3 to
    # 7 obs touches per iteration, and the noise floor with it)
    assert retained < 2048, f"off path retained {retained} bytes"
    assert obs.snapshot(runtime_gauges=False)["metrics"] == {}
    assert obs.recent() == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_counter_gauge_label_series():
    obs.set_mode("on")
    obs.counter("hits", 2, algo="a")
    obs.counter("hits", 3, algo="a")
    obs.counter("hits", 7, algo="b")
    obs.gauge("level", 0.5, what="x")
    obs.gauge("level", 0.25, what="x")       # gauges overwrite
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "hits", algo="a") == 5.0
    assert _value(snap, "hits", algo="b") == 7.0
    assert _value(snap, "level", what="x") == 0.25


def test_metric_kind_conflict_raises():
    obs.set_mode("on")
    obs.counter("twice")
    with pytest.raises(ValueError, match="already registered"):
        obs.gauge("twice", 1.0)


def test_histogram_bucket_edges():
    obs.set_mode("on")
    for v in (0.5, 1.0, 1.5, 2.0, 3.0):
        obs.observe("h", v, buckets=(1.0, 2.0))
    point = obs.snapshot(runtime_gauges=False)["metrics"]["h"]["points"][0]
    # value <= edge lands IN that bucket (le semantics): 0.5,1.0 | 1.5,2.0 | 3.0
    assert point["buckets"] == [1.0, 2.0]
    assert point["bucket_counts"] == [2, 2, 1]
    assert point["count"] == 5
    assert point["sum"] == pytest.approx(8.0)


def test_histogram_buckets_fixed_at_first_observation():
    obs.set_mode("on")
    obs.observe("fixed", 1.0, buckets=(10.0,))
    obs.observe("fixed", 100.0, buckets=(1.0, 2.0, 3.0))  # ignored
    point = obs.snapshot(runtime_gauges=False)["metrics"]["fixed"]["points"][0]
    assert point["buckets"] == [10.0]
    assert point["bucket_counts"] == [1, 1]


def test_unit_interval_bucket_preset():
    """ISSUE 19 satellite: the shared unit-interval preset for ratio
    histograms — monotone, capped at exactly 1.0, dense near the top
    where recall bands live (0.9/0.95/0.99 are resolvable edges)."""
    bs = obs.UNIT_BUCKETS
    assert bs[-1] == 1.0
    assert all(a < b for a, b in zip(bs, bs[1:]))
    assert all(0.0 < b <= 1.0 for b in bs)
    for edge in (0.9, 0.95, 0.99):
        assert edge in bs
    # consumers share the preset object, not a drifting copy
    from raft_tpu.serve.batcher import FILL_BUCKETS

    assert FILL_BUCKETS is obs.UNIT_BUCKETS
    obs.set_mode("on")
    obs.observe("serve.batch_fill_ratio", 0.93,
                buckets=FILL_BUCKETS, index="t")
    obs.observe("serve.recall_sample", 1.0,
                buckets=obs.UNIT_BUCKETS, index="t", rung="all")
    snap = obs.snapshot(runtime_gauges=False)["metrics"]
    for name in ("serve.batch_fill_ratio", "serve.recall_sample"):
        assert snap[name]["points"][0]["buckets"] == list(bs)
    fill = snap["serve.batch_fill_ratio"]["points"][0]
    # 0.93 resolves into (0.925, 0.95] — the band-adjacent bucket
    assert fill["bucket_counts"][bs.index(0.95)] == 1
    recall = snap["serve.recall_sample"]["points"][0]
    # perfect recall lands IN 1.0 (le semantics), not the overflow slot
    assert recall["bucket_counts"][bs.index(1.0)] == 1
    assert recall["bucket_counts"][-1] == 0


# ---------------------------------------------------------------------------
# Prometheus exposition round-trip
# ---------------------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'            # metric name
    r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})?'  # labels
    r' (-?(?:[0-9.]+(?:e[+-]?[0-9]+)?|[+-]?Inf|NaN))$',
    re.IGNORECASE,
)


def _parse_prometheus(text):
    """Tiny exposition-format checker: every line must be a # TYPE/HELP
    comment or a valid sample; returns {name: kind} and sample tuples."""
    types, samples = {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                         r"(counter|gauge|histogram)$", line)
            assert m, f"bad comment line: {line!r}"
            types[m.group(1)] = m.group(2)
            continue
        m = _PROM_SAMPLE.match(line)
        assert m, f"invalid sample line: {line!r}"
        samples.append((m.group(1), m.group(2) or "", float(m.group(3))))
    return types, samples


def test_prometheus_round_trip():
    obs.set_mode("on")
    obs.counter("queries_total", 8, algo="ivf_pq")
    obs.gauge("shard_coverage", 0.875, what="sharded_knn")
    obs.observe("search_latency_ms", 1.7, algo="ivf_pq")
    obs.observe("search_latency_ms", 300.0, algo="ivf_pq")
    obs.gauge("odd name!", 1.0, **{"with": 'quo"te\nline'})
    text = obs.export_prometheus()
    types, samples = _parse_prometheus(text)
    assert types["raft_tpu_queries_total"] == "counter"
    assert types["raft_tpu_shard_coverage"] == "gauge"
    assert types["raft_tpu_search_latency_ms"] == "histogram"
    assert types["raft_tpu_odd_name_"] == "gauge"
    by = {(n, l): v for n, l, v in samples}
    assert by[("raft_tpu_queries_total", '{algo="ivf_pq"}')] == 8
    # histogram: cumulative buckets, +Inf == count, sum present
    buckets = [(l, v) for n, l, v in samples
               if n == "raft_tpu_search_latency_ms_bucket"]
    vals = [v for _, v in buckets]
    assert vals == sorted(vals), "bucket counts must be cumulative"
    assert buckets[-1][0].endswith('le="+Inf"}') and buckets[-1][1] == 2
    assert by[("raft_tpu_search_latency_ms_count", '{algo="ivf_pq"}')] == 2
    assert by[("raft_tpu_search_latency_ms_sum",
               '{algo="ivf_pq"}')] == pytest.approx(301.7)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_single_thread():
    obs.set_mode("on")
    with obs.span("root", stage="x") as sp:
        with obs.span("child"):
            with obs.span("grandchild"):
                pass
        sp.set(rows=10)
    (thread, tree), = obs.recent()
    assert tree["name"] == "root"
    assert tree["attrs"] == {"stage": "x", "rows": 10}
    assert tree["ms"] >= 0
    assert tree["children"][0]["name"] == "child"
    assert tree["children"][0]["children"][0]["name"] == "grandchild"


def test_span_error_attr_and_stack_healing():
    obs.set_mode("on")
    with pytest.raises(RuntimeError):
        with obs.span("boom"):
            raise RuntimeError("x")
    (_, tree), = obs.recent()
    assert tree["attrs"]["error"] == "RuntimeError"
    assert obs.current() is None


def test_span_nesting_across_threads():
    obs.set_mode("on")
    barrier = threading.Barrier(2)
    errors = []

    def worker(tag):
        try:
            with obs.span(f"root-{tag}"):
                barrier.wait(timeout=10)     # both roots live concurrently
                with obs.span(f"child-{tag}"):
                    barrier.wait(timeout=10)  # both children live too
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(t,), name=f"w{t}")
          for t in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errors
    trees = {tree["name"]: (thread, tree) for thread, tree in obs.recent()}
    for tag in ("a", "b"):
        thread, tree = trees[f"root-{tag}"]
        assert thread == f"w{tag}"
        # a cross-thread leak would parent child-a under root-b (or lose it)
        assert [c["name"] for c in tree.get("children", [])] == \
            [f"child-{tag}"]


def test_span_child_cap_records_drops():
    obs.set_mode("on")
    with obs.span("root"):
        for i in range(obs_spans.MAX_CHILDREN + 5):
            with obs.span(f"c{i}"):
                pass
    (_, tree), = obs.recent()
    assert len(tree["children"]) == obs_spans.MAX_CHILDREN
    assert tree["dropped_children"] == 5


def test_entry_span_emits_search_metrics():
    obs.set_mode("on")
    with obs.entry_span("search", "demo", queries=12, k=5):
        pass
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "queries_total", algo="demo") == 12.0
    hist = snap["metrics"]["search_latency_ms"]["points"][0]
    assert hist["labels"] == {"algo": "demo"} and hist["count"] == 1


def test_entry_span_failure_emits_no_entry_metrics():
    obs.set_mode("on")
    with pytest.raises(ValueError):
        with obs.entry_span("search", "demo", queries=12):
            raise ValueError("boom")
    snap = obs.snapshot(runtime_gauges=False)
    assert "queries_total" not in snap["metrics"]
    assert _value(snap, "span_ms", name="demo.search") is not None


def test_legacy_trace_ranges_are_thread_local():
    from raft_tpu.core import trace

    trace.push_range("main-range")
    try:
        done = threading.Event()

        def worker():
            trace.push_range("worker-range")
            trace.pop_range()
            # a second pop on THIS thread must find an empty local stack,
            # not main's range (the pre-fix module-global bug)
            trace.pop_range()
            done.set()

        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10)
        assert done.is_set()
        assert len(trace._range_stack()) == 1    # main's range survived
    finally:
        trace.pop_range()
    assert trace._range_stack() == []


def test_trace_annotate_feeds_obs_spans():
    from raft_tpu.core import trace

    obs.set_mode("on")
    with trace.annotate("legacy-range"):
        pass
    assert obs.recent()[-1][1]["name"] == "legacy-range"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_and_manual_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.set_mode("flight")
    obs.counter("queries_total", 4, algo="x")
    with obs.span("s"):
        pass
    obs.event("custom", detail=1)
    kinds = [e["kind"] for e in obs.flight_events()]
    assert "metric" in kinds and "span" in kinds and "event" in kinds
    path = obs.flight_dump()
    assert path.startswith(str(tmp_path))
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[-1]["kind"] == "snapshot"
    assert "queries_total" in lines[-1]["metrics"]


def test_flight_auto_dump_on_dead_backend_classification(tmp_path,
                                                         monkeypatch):
    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.set_mode("flight")
    obs.counter("queries_total", 1, algo="x")
    resilience.classify(resilience.DeadBackendError("axon went dark"))
    path = obs.last_dump_path()
    assert path is not None and os.path.exists(path)
    lines = [json.loads(ln) for ln in open(path)]
    err = [e for e in lines if e["kind"] == "error"]
    assert err and err[0]["error_kind"] == "dead_backend"
    # once per process: a second fatal must not overwrite the artifact
    resilience.classify(ValueError("later fatal"))
    assert obs.last_dump_path() == path


def test_flight_dump_on_injected_dead_stage_search(tmp_path, monkeypatch):
    """The ISSUE satellite scenario: a dead@stage:search fault mid-stream
    leaves a post-mortem JSONL even though the retry recovers the job."""
    from raft_tpu.neighbors import ivf_flat, stream

    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.set_mode("flight")
    rng = np.random.default_rng(7)
    x = rng.standard_normal((256, 8), np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=2), x)
    sp = ivf_flat.SearchParams(n_probes=2, scan_impl="xla")
    q = x[:64]
    ref_d, ref_i = stream.search_host_array(ivf_flat, sp, idx, q, 5,
                                            batch_rows=16)
    with faultinject.inject("dead@stage:search"):
        d, i = stream.search_host_array(ivf_flat, sp, idx, q, 5,
                                        batch_rows=16, backoff_s=0.01)
    np.testing.assert_array_equal(i, ref_i)      # retry recovered the job
    path = obs.last_dump_path()
    assert path is not None and os.path.exists(path)
    lines = [json.loads(ln) for ln in open(path)]
    assert any(e["kind"] == "error" and e["error_kind"] == "dead_backend"
               for e in lines)
    assert any(e["kind"] == "event" and e.get("event") == "fault_injected"
               for e in lines)
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "retries", kind="dead_backend") >= 1


# ---------------------------------------------------------------------------
# graft-trace: context, wire format, waterfalls (ISSUE 13)
# ---------------------------------------------------------------------------


def test_trace_context_wire_round_trip():
    obs.set_mode("on")
    ctx = obs.start_trace("fabric.search", index="default", k=4)
    assert ctx is not None and ctx.trace_id != ctx.parent_span_id
    wire = obs.trace.to_wire(ctx)
    assert wire == {"trace_id": ctx.trace_id,
                    "parent_span_id": ctx.parent_span_id}
    back = obs.trace.adopt(wire)
    assert back.trace_id == ctx.trace_id
    assert back.parent_span_id == ctx.parent_span_id
    # malformed wire degrades to untraced, never raises
    assert obs.trace.adopt(None) is None
    assert obs.trace.adopt("garbage") is None
    assert obs.trace.adopt({"trace_id": 7}) is None


def test_trace_ids_unique_across_mints():
    obs.set_mode("on")
    ids = {obs.start_trace("e").trace_id for _ in range(64)}
    assert len(ids) == 64


def test_traced_payload_injects_wire_field():
    obs.set_mode("on")
    ctx = obs.start_trace("e")
    p = obs.traced_payload({"q": 1}, ctx)
    assert p["q"] == 1 and p["trace"]["trace_id"] == ctx.trace_id
    # ambient context used when none passed
    with obs.trace.activate(ctx):
        p2 = obs.traced_payload({"k": 2})
    assert p2["trace"]["trace_id"] == ctx.trace_id
    # no context anywhere: payload unchanged
    p3 = {"k": 3}
    assert obs.traced_payload(p3) is p3


def test_trace_activate_is_thread_local_and_restores():
    obs.set_mode("on")
    ctx = obs.start_trace("e")
    seen = []

    def worker():
        seen.append(obs.trace.current())

    with obs.trace.activate(ctx):
        assert obs.trace.current() is ctx
        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10)
        with obs.span("inner"):
            pass
    assert obs.trace.current() is None
    assert seen == [None]            # ambient context never leaks threads
    # the span opened under the activated context adopted its trace id
    assert obs.recent()[-1][1]["attrs"]["trace_id"] == ctx.trace_id


def test_waterfall_assembly_and_report():
    obs.set_mode("on")
    ctx = obs.start_trace("fabric.search", k=4)
    obs.trace.stage(ctx, "rpc", ms=2.0, worker=0, shard=0)
    obs.trace.stage(ctx, "rpc", ms=3.0, worker=1, shard=0,
                    status="hedge_win")
    obs.trace.stage(ctx, "worker_scan", ms=1.5, worker=1, shard=0,
                    device_complete=True)
    obs.trace.stage(ctx, "merge", ms=0.5)
    wf = obs.trace.finish(ctx, coverage_min=1.0)
    assert wf["status"] == "ok" and wf["ms"] >= 0
    assert [s["stage"] for s in wf["stages"]] == [
        "rpc", "rpc", "worker_scan", "merge"]
    assert wf["stages"][1]["status"] == "hedge_win"
    assert wf["attrs"]["coverage_min"] == 1.0
    # the report finds it, by id and in bulk; late stages are dropped
    assert obs.trace_report(trace_id=wf["trace_id"]) == [wf]
    assert obs.trace_report() == [wf]
    obs.trace.stage(ctx, "rpc", ms=9.0)        # after finish: ignored
    assert len(wf["stages"]) == 4
    assert obs.trace.finish(ctx) is None       # double finish: no-op


def test_waterfall_stage_cap_records_drops():
    obs.set_mode("on")
    ctx = obs.start_trace("e")
    for i in range(obs_trace.MAX_STAGES + 7):
        obs.trace.stage(ctx, "rpc", ms=1.0)
    wf = obs.trace.finish(ctx)
    assert len(wf["stages"]) == obs_trace.MAX_STAGES
    assert wf["dropped_stages"] == 7


def test_waterfall_flight_record_and_counter(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.set_mode("flight")
    ctx = obs.start_trace("fabric.search")
    obs.trace.stage(ctx, "merge", ms=0.1)
    obs.trace.finish(ctx)
    evts = [e for e in obs.flight_events() if e["kind"] == "waterfall"]
    assert len(evts) == 1 and evts[0]["trace_id"] == ctx.trace_id
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "trace.waterfalls_total", status="ok") == 1.0


def test_ring_stats_counts_evictions_honestly():
    obs.set_mode("on")
    for _ in range(5):
        obs.trace.finish(obs.start_trace("e"))
    s = obs_trace.ring_stats()
    assert s == {"completed_total": 5, "retained": 5, "evicted": 0}
    # shrink the window to force eviction (restored after)
    import collections as _c

    orig = obs_trace._done
    obs_trace._done = _c.deque(orig, maxlen=3)
    try:
        obs.trace.finish(obs.start_trace("e"))
        s = obs_trace.ring_stats()
        assert s["completed_total"] == 6 and s["retained"] == 3
        assert s["evicted"] == 3          # truncation is VISIBLE
    finally:
        obs_trace._done = _c.deque(obs_trace._done, maxlen=obs_trace.MAX_DONE)
    obs.reset()
    assert obs_trace.ring_stats()["completed_total"] == 0


def test_waterfall_complete_predicate():
    """The ONE completeness definition the chaos acceptance and the
    loadgen columns share."""
    base = {"status": "ok",
            "attrs": {"covered_shards": [0, 1]},
            "stages": [
                {"stage": "worker_scan", "shard": 0,
                 "device_complete": True},
                {"stage": "worker_scan", "shard": 1,
                 "device_complete": True},
                {"stage": "merge"},
            ]}
    assert obs_trace.waterfall_complete(base)
    import copy

    failed = copy.deepcopy(base)
    failed["status"] = "failed"
    assert not obs_trace.waterfall_complete(failed)
    no_merge = copy.deepcopy(base)
    no_merge["stages"] = no_merge["stages"][:2]
    assert not obs_trace.waterfall_complete(no_merge)
    missing_scan = copy.deepcopy(base)
    missing_scan["stages"][1]["shard"] = 0
    assert not obs_trace.waterfall_complete(missing_scan)
    not_device = copy.deepcopy(base)
    not_device["stages"][0]["device_complete"] = False
    assert not obs_trace.waterfall_complete(not_device)
    # a degraded answer with all ITS covered shards scanned is complete
    degraded = copy.deepcopy(base)
    degraded["status"] = "degraded"
    degraded["attrs"]["covered_shards"] = [0]
    degraded["stages"] = [base["stages"][0], {"stage": "merge"}]
    assert obs_trace.waterfall_complete(degraded)


def test_stage_stats_percentiles_and_hedge_counts():
    obs.set_mode("on")
    for i in range(10):
        ctx = obs.start_trace("e")
        obs.trace.stage(ctx, "rpc", ms=float(i + 1), worker=0)
        obs.trace.finish(ctx)
    ctx = obs.start_trace("e")
    obs.trace.stage(ctx, "rpc", ms=100.0, status="hedge_win")
    obs.trace.stage(ctx, "rpc", status="hedge_loser")
    obs.trace.stage(ctx, "rpc", ms=5.0, status="failed", kind="transient")
    obs.trace.stage(ctx, "retry", status="retry")
    obs.trace.finish(ctx)
    stats = obs_trace.stage_stats(obs.trace_report())
    rpc = stats["rpc"]
    assert rpc["count"] == 13
    assert rpc["hedge_wins"] == 1 and rpc["hedge_losers"] == 1
    assert rpc["failed"] == 1
    # percentiles over ok + hedge_win samples only (failed ms excluded)
    assert rpc["p50_ms"] == 6.0 and rpc["p99_ms"] == 100.0
    assert stats["retry"]["retries"] == 1
    assert stats["retry"]["p50_ms"] is None


def test_flight_dump_same_second_paths_do_not_collide(tmp_path,
                                                      monkeypatch):
    """ISSUE 13 satellite: two dumps from one process in the same
    wall-clock second used to compute the SAME default path and the
    second silently overwrote the first — the monotonic per-process
    sequence suffix keeps every default path distinct."""
    monkeypatch.setenv("RAFT_TPU_OBS_DIR", str(tmp_path))
    obs.set_mode("flight")
    # pin the clock so both paths share the <unix> component for sure
    monkeypatch.setattr(obs_flight.time, "time", lambda: 1234567890.0)
    obs.counter("queries_total", 1, algo="a")
    p1 = obs.flight_dump()
    obs.counter("queries_total", 1, algo="b")
    p2 = obs.flight_dump()
    assert p1 != p2
    assert os.path.exists(p1) and os.path.exists(p2)
    # both artifacts intact (the first was NOT overwritten)
    first = [json.loads(ln) for ln in open(p1)]
    second = [json.loads(ln) for ln in open(p2)]
    assert first[-1]["kind"] == "snapshot"
    assert len(second) > len(first)


def test_federation_merge_and_prometheus_render():
    obs.set_mode("on")
    obs.counter("queries_total", 4, algo="x")
    obs.observe("search_latency_ms", 2.0, algo="x")
    m = obs.snapshot(runtime_gauges=False)["metrics"]
    fed = obs_federation.federated_snapshot({"w0": m, "w1": m})
    assert fed["workers"] == ["w0", "w1"]
    pts = fed["metrics"]["queries_total"]["points"]
    assert {p["labels"]["worker"] for p in pts} == {"w0", "w1"}
    assert all(p["labels"]["algo"] == "x" for p in pts)
    text = obs_federation.render_prometheus(fed["metrics"])
    _parse_prometheus(text)          # valid exposition format
    assert 'raft_tpu_queries_total{algo="x",worker="w0"} 4' in text
    # histogram rendered cumulatively with +Inf == count per worker
    assert text.count('le="+Inf"') == 2


def test_federation_kind_conflict_kept_out_of_exposition():
    fed = obs_federation.merge_metric_maps({
        "a": {"m": {"kind": "counter",
                    "points": [{"labels": {}, "value": 1.0}]}},
        "b": {"m": {"kind": "gauge",
                    "points": [{"labels": {}, "value": 2.0}]}},
    })
    assert len(fed["m"]["points"]) == 1          # first kind wins
    assert "_conflicts" in fed
    text = obs_federation.render_prometheus(fed)
    assert "conflicts" not in text               # meta never exported
    _parse_prometheus(text)


# ---------------------------------------------------------------------------
# resilience + tuning wiring
# ---------------------------------------------------------------------------


def test_errors_total_counts_one_failure_once_across_nested_layers():
    """stream.py nests run_halving around resilience.run — both classify
    the SAME exception; errors_total must advance once, not per layer."""
    obs.set_mode("on")
    e = MemoryError("RESOURCE_EXHAUSTED: one failure")
    assert resilience.classify(e) == resilience.OOM
    assert resilience.classify(e) == resilience.OOM   # nested re-classify
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "errors_total", kind="oom") == 1.0
    # a DISTINCT later failure still counts
    resilience.classify(MemoryError("RESOURCE_EXHAUSTED: another"))
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "errors_total", kind="oom") == 2.0


def test_retry_counter_and_events():
    obs.set_mode("on")
    calls = []

    def flaky():
        if not calls:
            calls.append(1)
            raise resilience.TransientError("UNAVAILABLE: blip")
        return 42

    assert resilience.run(flaky, retries=2, backoff_s=0.01) == 42
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "retries", kind="transient") == 1.0
    assert _value(snap, "errors_total", kind="transient") >= 1.0


def test_oom_ladder_downshift_counter():
    obs.set_mode("on")

    calls = []

    def searcher(batch):
        if len(batch) > 8:
            calls.append(len(batch))
            raise MemoryError("RESOURCE_EXHAUSTED: injected")
        return jnp.asarray(np.asarray(batch) * 2.0)

    out, survived = resilience.degrade.run_halving(
        searcher, jnp.arange(32.0), budget_name="obs_test_budget")
    assert survived == 8
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "oom_ladder_downshifts", path="halving") >= 1.0
    assert _value(snap, "runtime_budget", budget="obs_test_budget") == 8.0


def test_checkpoint_save_resume_counters(tmp_path):
    obs.set_mode("on")
    ck = resilience.StreamCheckpoint(str(tmp_path))
    ck.save("search", 3, {"rows_done": 48}, {"d": np.zeros((48, 5))},
            fingerprint={"k": 5})
    assert ck.load(fingerprint={"k": 5}) is not None
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "checkpoint_saves", phase="search") == 1.0
    assert _value(snap, "checkpoint_resumes", phase="search") == 1.0


def test_tuning_dispatch_counter():
    obs.set_mode("on")
    from raft_tpu.matrix.select_k import dispatch_select_impl

    impl = dispatch_select_impl(4, 4096, 512, jnp.float32)
    snap = obs.snapshot(runtime_gauges=False)
    pts = snap["metrics"]["tuning.dispatch"]["points"]
    assert any(p["labels"]["op"] == "select_k"
               and p["labels"]["impl"] == impl for p in pts)


def test_recompile_hook_counts_new_traces():
    import jax

    obs.set_mode("on")
    from raft_tpu.matrix.select_k import select_k

    jax.clear_caches()
    select_k(jnp.asarray(np.random.rand(4, 128).astype(np.float32)), 8)
    obs.capture_runtime_gauges()                 # baseline cache sizes
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "jit_cache_entries",
                  fn="select_k._select_k") is not None
    select_k(jnp.asarray(np.random.rand(4, 256).astype(np.float32)), 8)
    obs.capture_runtime_gauges()                 # growth -> recompiles
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "recompiles", fn="select_k._select_k") >= 1.0
    # steady state: re-running the SAME shape adds nothing
    before = _value(snap, "recompiles", fn="select_k._select_k")
    select_k(jnp.asarray(np.random.rand(4, 256).astype(np.float32)), 8)
    obs.capture_runtime_gauges()
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "recompiles", fn="select_k._select_k") == before


def test_write_snapshot_sidecar(tmp_path):
    obs.set_mode("on")
    obs.counter("queries_total", 3, algo="x")
    path = obs.write_snapshot(str(tmp_path / "BENCH_x.obs.json"))
    data = json.load(open(path))
    assert data["mode"] == "on"
    assert data["metrics"]["queries_total"]["points"][0]["value"] == 3.0


# ---------------------------------------------------------------------------
# acceptance: instrumented ivf_pq under faults + sharded coverage
# ---------------------------------------------------------------------------


def test_acceptance_ivf_pq_build_search_under_oom(tmp_path):
    """ISSUE 4 acceptance: RAFT_TPU_OBS=on + an ivf_pq build+search run
    under injected oom@chunk faults yields a snapshot with non-zero
    queries_total, search_latency_ms histogram counts, and
    oom_ladder_downshifts, and a valid Prometheus exposition."""
    from raft_tpu.neighbors import ivf_pq, stream

    obs.set_mode("on")
    rng = np.random.default_rng(3)
    x = rng.standard_normal((512, 16), np.float32)
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=2)
    idx = ivf_pq.build(params, x)
    sp = ivf_pq.SearchParams(n_probes=4, scan_impl="xla")
    ref_d, ref_i = stream.search_host_array(ivf_pq, sp, idx, x[:128], 5,
                                            batch_rows=32)
    with faultinject.inject("oom@chunk:1"):
        d, i = stream.search_host_array(ivf_pq, sp, idx, x[:128], 5,
                                        batch_rows=32)
    np.testing.assert_array_equal(i, ref_i)      # ladder output is bitwise
    snap = obs.snapshot()
    assert _value(snap, "queries_total", algo="ivf_pq") > 0
    hists = snap["metrics"]["search_latency_ms"]["points"]
    assert sum(p["count"] for p in hists) > 0
    assert _value(snap, "oom_ladder_downshifts", path="halving") >= 1.0
    assert _value(snap, "builds_total", algo="ivf_pq") == 1.0
    _parse_prometheus(obs.export_prometheus())   # valid exposition format


def test_acceptance_sharded_coverage_gauge(eight_device_mesh):
    """Per-shard degradation shows up as the shard_coverage gauge (and a
    dropout counter) without the caller lifting a finger."""
    from raft_tpu.comms import sharded

    obs.set_mode("on")
    rng = np.random.default_rng(5)
    x = rng.standard_normal((64, 8), np.float32)
    q = x[:4]
    with faultinject.inject("shard@rank:2"):
        d, i, cov = sharded.sharded_knn(q, x, 3, eight_device_mesh,
                                        partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "shard_coverage",
                  what="sharded_knn") == pytest.approx(7 / 8)
    assert _value(snap, "shard_dropouts_total", what="sharded_knn") == 1.0
    assert _value(snap, "queries_total", algo="sharded_knn") == 4.0


def test_sharded_full_coverage_gauge_recorded(eight_device_mesh):
    """The PLAIN (no validity scan) path records shard_coverage = 1 too
    — a dashboard must distinguish "healthy 8/8" from "metric never
    emitted" (ISSUE 6 satellite)."""
    from raft_tpu.comms import sharded

    obs.set_mode("on")
    rng = np.random.default_rng(6)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    q = x[:4]
    d, i = sharded.sharded_knn(q, x, 3, eight_device_mesh)
    snap = obs.snapshot(runtime_gauges=False)
    assert _value(snap, "shard_coverage", what="sharded_knn") == 1.0
