"""graft-flow prefetch pipeline (ISSUE 16): unit semantics of
raft_tpu.core.pipeline plus on-vs-off bitwise acceptance on every wired
streaming path — host-array search, tiered refined search, streamed
build, and the serving dispatcher — with the fault-injection legs
(OOM ladder, kill+resume, slow fetch) run at depth > 1 so prefetched
chunks are actually in flight when the fault strikes."""

import threading
import time

import numpy as np
import pytest

from raft_tpu import obs, serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.core import pipeline
from raft_tpu.core.interruptible import Interruptible, InterruptedException
from raft_tpu.neighbors import brute_force, ivf_pq, tiered
from raft_tpu.neighbors.stream import search_host_array
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.resilience import faultinject

pytestmark = [pytest.mark.threadsan]


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    # sanitized locks: every pipeline/serve lock in this suite goes
    # through lockwatch, so the whole file doubles as the THREADSAN leg
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    faultinject.clear()
    yield
    faultinject.clear()
    tuning.reload()


def _no_prefetch_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("raft-tpu-prefetch")]


# ---------------------------------------------------------------------------
# Prefetcher units
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth", [0, 1, 2, 4])
def test_ordering_every_depth(depth):
    with pipeline.Prefetcher(lambda: iter(range(20)), depth=depth) as pf:
        assert list(pf) == list(range(20))
    assert _no_prefetch_threads() == []


def test_off_mode_spawns_no_thread():
    before = set(threading.enumerate())
    with pipeline.Prefetcher(lambda: iter(range(5)), depth=0) as pf:
        assert list(pf) == [0, 1, 2, 3, 4]
        assert set(threading.enumerate()) == before


def test_resolve_depth():
    assert pipeline.resolve_depth(3) == 3
    assert pipeline.resolve_depth(-1) == 0          # clamped, never negative
    assert pipeline.resolve_depth(None) == pipeline.DEFAULT_DEPTH


def test_producer_error_surfaces_at_consuming_next():
    """The ORIGINAL exception object crosses the thread boundary and
    raises at the iteration that would have consumed the bad item —
    classification (resilience.errors / faultinject types) survives."""
    boom = faultinject.InjectedOOM("RESOURCE_EXHAUSTED: injected")

    def source():
        yield 0
        yield 1
        raise boom

    with pipeline.Prefetcher(source, depth=2) as pf:
        it = iter(pf)
        assert next(it) == 0
        assert next(it) == 1
        with pytest.raises(faultinject.InjectedOOM) as ei:
            next(it)
        assert ei.value is boom
    assert _no_prefetch_threads() == []


def test_cross_thread_cancel_joins_producer_promptly():
    """cancel() from another thread unparks a stalled consumer and the
    producer thread is gone shortly after — GL014's no-leak contract."""
    tok = Interruptible()

    def source():
        yield 0
        while True:                     # producer that would run forever
            time.sleep(0.01)
            yield 1

    pf = pipeline.Prefetcher(source, depth=1, token=tok)
    it = iter(pf)
    assert next(it) == 0
    threading.Timer(0.05, tok.cancel).start()
    t0 = time.perf_counter()
    with pytest.raises(InterruptedException):
        while True:
            next(it)
    assert time.perf_counter() - t0 < 5.0
    pf.close()
    deadline = time.time() + 5.0
    while _no_prefetch_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert _no_prefetch_threads() == []


def test_flush_restarts_from_mutated_source():
    """flush() drops buffered items and re-iterates the source — the OOM
    downshift hook: rewind/shrink, then flush, and in-flight chunks are
    re-read under the new geometry."""

    class Src:
        start = 0

        def __iter__(self):
            return iter(range(self.start, 10))

    src = Src()
    pf = pipeline.Prefetcher(src, depth=4)
    it = iter(pf)
    assert [next(it), next(it)] == [0, 1]
    src.start = 7
    pf.flush()
    assert list(pf) == [7, 8, 9]
    assert _no_prefetch_threads() == []


def test_stall_metric_and_stats():
    obs.set_mode("on")
    obs_metrics.reset()
    try:
        def slow_source():
            for i in range(4):
                time.sleep(0.01)
                yield i

        with pipeline.Prefetcher(slow_source, depth=0, path="t.off") as pf:
            list(pf)
            off = pf.stats()
        assert off["depth"] == 0
        # off mode books the full inline read time as stall
        assert off["stall_ms"] >= 4 * 10 * 0.5
        assert off["items"] == 4
        snap = obs_metrics.snapshot(runtime_gauges=False)
        paths = {p["labels"].get("path")
                 for p in snap["metrics"]["pipeline.stall_ms"]["points"]}
        assert "t.off" in paths

        with pipeline.Prefetcher(slow_source, depth=2, path="t.on") as pf:
            for _ in pf:
                time.sleep(0.02)        # consumer slower than producer
            on = pf.stats()
        assert on["items"] == 4
        assert 0.0 <= on["occupancy"] <= 2.0
        # producer stays ahead: stall well under the serial read time
        assert on["stall_ms"] < off["stall_ms"]
    finally:
        obs.set_mode(None)
        obs_metrics.reset()


def test_overlap_chains_stages_in_order():
    calls = []

    def upload(x):
        calls.append(("u", x))
        return x * 10

    def compute(x):
        calls.append(("c", x))
        return x + 1

    out = pipeline.overlap(lambda: iter(range(6)), upload, compute, depth=2)
    with out:
        assert list(out) == [i * 10 + 1 for i in range(6)]
    assert [c for k, c in calls if k == "u"] == list(range(6))
    assert _no_prefetch_threads() == []


# ---------------------------------------------------------------------------
# wired path 1: host-array streaming search
# ---------------------------------------------------------------------------

_N, _D, _M, _K, _BATCH = 600, 24, 300, 10, 64


class _BF:
    @staticmethod
    def search(sp, index, batch, k):
        return brute_force.search(index, batch, k)


@pytest.fixture(scope="module")
def stream_data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((_N, _D)).astype(np.float32)
    q = rng.standard_normal((_M, _D)).astype(np.float32)
    return x, q, brute_force.build(x)


def test_stream_on_vs_off_bitwise(stream_data):
    x, q, index = stream_data
    base = search_host_array(_BF, None, index, q, _K, batch_rows=_BATCH,
                             pipeline_depth=0)
    for depth in (1, 2, 4):
        d, i = search_host_array(_BF, None, index, q, _K,
                                 batch_rows=_BATCH, pipeline_depth=depth)
        assert np.array_equal(d, base[0]) and np.array_equal(i, base[1])


def test_stream_oom_ladder_bitwise_with_prefetch_in_flight(stream_data):
    """oom@chunk strikes the CONSUMING dispatch while later chunks are
    already prefetched; the downshift rewinds + flushes and the result
    stays bitwise with the uninjected run."""
    x, q, index = stream_data
    base_d, base_i = search_host_array(_BF, None, index, q, _K,
                                       batch_rows=_BATCH, pipeline_depth=0)
    with faultinject.inject("oom@chunk:2"):
        d, i = search_host_array(_BF, None, index, q, _K, batch_rows=_BATCH,
                                 backoff_s=0.001, pipeline_depth=2)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)
    assert tuning.runtime_budget("stream_batch_rows") == _BATCH // 2


def test_stream_kill_resume_bitwise_with_prefetch_in_flight(
        stream_data, tmp_path):
    """A kill at chunk 3 with depth=2 (chunks 4/5 prefetched but
    unscored) checkpoints only CONSUMED rows; resume is bitwise."""
    import json
    import os

    x, q, index = stream_data
    base_d, base_i = search_host_array(_BF, None, index, q, _K,
                                       batch_rows=_BATCH, pipeline_depth=0)
    ckdir = str(tmp_path / "ck")
    with faultinject.inject("dead@chunk:3"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            search_host_array(_BF, None, index, q, _K, batch_rows=_BATCH,
                              checkpoint_dir=ckdir, checkpoint_every=1,
                              retries=0, pipeline_depth=2)
    manifest = json.load(open(os.path.join(ckdir, "manifest.json")))
    # prefetched-but-unscored chunks are NOT in the checkpoint
    assert manifest["meta"]["rows_done"] == 3 * _BATCH
    d, i = search_host_array(_BF, None, index, q, _K, batch_rows=_BATCH,
                             checkpoint_dir=ckdir, resume=True,
                             pipeline_depth=2)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


def test_stream_slow_fetch_overlap_speedup(stream_data, monkeypatch):
    """With an injected slow read AND slow dispatch (40 ms each), the
    serial run pays both per chunk while depth=2 overlaps them — the
    in-suite version of the PIPE_r16.json acceptance measurement."""
    x, q, index = stream_data
    monkeypatch.setenv("RAFT_TPU_FAULTS_SLOW_MS", "40")
    spec = "slow@stage:stream.read*100,slow@stage:search*100"
    search_host_array(_BF, None, index, q, _K, batch_rows=_BATCH,
                      pipeline_depth=0)       # warm compile out of the timing
    with faultinject.inject(spec):
        t0 = time.perf_counter()
        base = search_host_array(_BF, None, index, q, _K,
                                 batch_rows=_BATCH, pipeline_depth=0)
        serial_s = time.perf_counter() - t0
    with faultinject.inject(spec):
        t0 = time.perf_counter()
        over = search_host_array(_BF, None, index, q, _K,
                                 batch_rows=_BATCH, pipeline_depth=2)
        overlap_s = time.perf_counter() - t0
    assert np.array_equal(base[0], over[0])
    assert np.array_equal(base[1], over[1])
    # ~5 chunks x 80ms serial vs ~5 x 40ms overlapped; generous margin
    # for CI noise
    assert serial_s > 1.25 * overlap_s, (serial_s, overlap_s)


# ---------------------------------------------------------------------------
# wired path 2: tiered refined search (fetch/score overlap)
# ---------------------------------------------------------------------------

def test_refined_stream_on_vs_off_bitwise():
    rng = np.random.default_rng(11)
    n, d, m = 3000, 32, 500
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=4,
                           kmeans_trainset_fraction=1.0), x)
    sp = ivf_pq.SearchParams(n_probes=16)
    outs = {}
    for depth in (0, 2, 4):
        # fresh source per depth: promotion state is traffic-dependent
        # accounting, but scored VALUES must not depend on it
        src = tiered.HostArraySource(x, hot_rows=512, promote_after=1,
                                     promote_batch=128)
        outs[depth] = ivf_pq.search_refined_stream(
            sp, idx, q, 10, refine_ratio=2, dataset=src,
            batch_rows=128, pipeline_depth=depth)
    for depth in (2, 4):
        assert np.array_equal(outs[depth][0], outs[0][0]), depth
        assert np.array_equal(outs[depth][1], outs[0][1]), depth


def test_refined_stream_slow_fetch_injection_attributes_to_consumer():
    """A fault spec scoped to the fetch stage strikes (on the producer
    thread at depth>0) and still surfaces at the consuming iteration."""
    rng = np.random.default_rng(12)
    n, d = 1500, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((100, d)).astype(np.float32)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=16, kmeans_n_iters=4,
                           kmeans_trainset_fraction=1.0), x)
    sp = ivf_pq.SearchParams(n_probes=8)
    src = tiered.HostArraySource(x, hot_rows=256)
    with faultinject.inject("dead@stage:tiered.fetch"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            ivf_pq.search_refined_stream(sp, idx, q, 10, dataset=src,
                                         batch_rows=32, pipeline_depth=2)
    assert _no_prefetch_threads() == []


# ---------------------------------------------------------------------------
# wired path 3: streamed build
# ---------------------------------------------------------------------------

def test_build_streamed_on_vs_off_bitwise():
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    n, d, bs = 4000, 32, 1024
    x = rng.standard_normal((n, d)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)

    def make_batches():
        xd = jnp.asarray(x)
        npad = -(-n // bs) * bs
        xp = jnp.pad(xd, ((0, npad - n), (0, 0)))
        for off in range(0, npad, bs):
            yield xp[off:off + bs]

    off = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                                pipeline_depth=0)
    on = ivf_pq.build_streamed(params, make_batches, n, d, trainset=x,
                               pipeline_depth=2)
    np.testing.assert_array_equal(np.asarray(on.list_sizes),
                                  np.asarray(off.list_sizes))
    np.testing.assert_array_equal(np.asarray(on.indices),
                                  np.asarray(off.indices))
    np.testing.assert_array_equal(np.asarray(on.codes),
                                  np.asarray(off.codes))
    assert _no_prefetch_threads() == []


# ---------------------------------------------------------------------------
# wired path 4: serving dispatcher
# ---------------------------------------------------------------------------

_SN, _SD = 320, 16


def _serve_params(depth, **kw):
    kw.setdefault("max_batch_rows", 16)
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("max_k", 8)
    return serve.ServeParams(pipeline_depth=depth, **kw)


def test_serve_pipeline_on_vs_off_matches_under_mutation():
    """Same mutation + query traffic against a pipelined and a
    synchronous server yields identical results — delete/upsert/swap
    invalidation holds with tickets in flight (each ticket pins its
    generation)."""
    rng = np.random.default_rng(21)
    x = rng.standard_normal((_SN, _SD)).astype(np.float32)
    x2 = rng.standard_normal((_SN, _SD)).astype(np.float32)
    q = rng.standard_normal((40, _SD)).astype(np.float32)
    up = rng.standard_normal((3, _SD)).astype(np.float32)
    outs = {}
    for depth in (0, 2):
        with serve.Server(_serve_params(depth)) as srv:
            srv.create_index("default", x)
            got = [srv.search(q[:7], 5)]
            srv.delete([1, 2, 3])
            got.append(srv.search(q[7:20], 5))
            srv.upsert(up, [_SN + 1, _SN + 2, _SN + 3])
            got.append(srv.search(q[20:31], 5))
            srv.swap("default", dataset=x2, wait=True)
            got.append(srv.search(q[31:], 5))
            outs[depth] = got
    for (d0, i0), (d2, i2) in zip(outs[0], outs[2]):
        assert np.array_equal(d0, d2)
        assert np.array_equal(i0, i2)


def test_serve_pipeline_trace_stable_under_mutation_traffic():
    """Steady-state serving with the dispatch pipeline on adds ZERO
    traces across delete/upsert traffic (the GL007 hook, pipelined)."""
    rng = np.random.default_rng(22)
    x = rng.standard_normal((_SN, _SD)).astype(np.float32)
    q = rng.standard_normal((24, _SD)).astype(np.float32)
    with serve.Server(_serve_params(2, max_wait_ms=0.5)) as srv:
        srv.create_index("default", x)
        srv.delete([1, 2])
        srv.search(q[:3], 4)
        before = serve.trace_cache_sizes()
        for rows in (1, 3, 7, 2, 11, 16, 5):
            block = rng.standard_normal((rows, _SD)).astype(np.float32)
            srv.search(block, 4)
        srv.delete([9])
        srv.search(q[:2], 3)
        srv.upsert(rng.standard_normal(_SD).astype(np.float32), [_SN + 9])
        srv.search(q[:5], 4)
        after = serve.trace_cache_sizes()
        assert after == before, (
            f"pipelined steady-state serving retraced: {before} -> {after}")


def test_serve_pipeline_concurrent_load_all_futures_resolve():
    """Concurrent submitters + a hot swap mid-flight: every future
    resolves (no ticket dropped, no pin leaked) and close() drains."""
    rng = np.random.default_rng(23)
    x = rng.standard_normal((_SN, _SD)).astype(np.float32)
    x2 = rng.standard_normal((_SN, _SD)).astype(np.float32)
    with serve.Server(_serve_params(2)) as srv:
        srv.create_index("default", x)
        futs = []
        errs = []

        def worker(wid):
            r = np.random.default_rng(wid)
            for _ in range(12):
                qb = r.standard_normal((3, _SD)).astype(np.float32)
                try:
                    futs.append(srv.submit(qb, 4))
                except Exception as e:     # noqa: BLE001
                    errs.append(e)

        ts = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
        for t in ts:
            t.start()
        srv.swap("default", dataset=x2, wait=True)
        for t in ts:
            t.join()
        assert not errs
        for f in futs:
            d, i = f.result(timeout=30)
            assert d.shape == (3, 4)
    # server closed: completion thread drained and gone
    deadline = time.time() + 5.0
    while any(t.name.startswith("serve-pipe")
              for t in threading.enumerate()) and time.time() < deadline:
        time.sleep(0.01)
    assert not any(t.name.startswith("serve-pipe")
                   for t in threading.enumerate())
