"""Unit tests for the fused CAGRA beam-step kernel (ops/beam_step.py),
run in pallas interpret mode on CPU (the on-chip path is bench-validated
plus covered by scripts/tpu_parity.py each round).

Oracle strategy mirrors the reference's CAGRA tests
(cpp/test/neighbors/ann_cagra.cuh): numpy re-implementation of one
merge step, plus recall-bound end-to-end runs against naive KNN.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu.ops.beam_step import beam_merge_step
from raft_tpu.neighbors import cagra
from raft_tpu.distance.types import DistanceType
from tests.oracles import eval_recall, naive_knn


# THE oracle lives in one home (the kernel-contract drivers) so this
# suite, the contract sweep, and tpu_parity's compiled rerun all judge
# the kernel against identical semantics: sort the concatenation, blank
# windowed duplicates IN PLACE (ghosts sink at the *next* iteration's
# sort, as in the XLA path), truncate to L, pick the first ``width``
# unexplored.
from raft_tpu.analysis.contract_drivers import (  # noqa: E402
    _np_beam_oracle as _np_merge_oracle,
)


def test_merge_step_matches_numpy_oracle():
    rng = np.random.default_rng(3)
    L, C, m, width = 16, 32, 128, 4
    # distance == id: globally unique distances, so ties happen ONLY
    # between duplicate ids (the windowed-dedup invariant); inject at
    # most one candidate duplicate per (buffer id, column) so duplicate
    # runs stay within the kernel's window
    bi = rng.permutation(np.arange(0, 4096))[: L * m].reshape(L, m)
    bi = bi.astype(np.int32)
    be = (rng.random((L, m)) < 0.5).astype(np.int32)
    ci = rng.permutation(np.arange(4096, 16384))[: C * m].reshape(C, m)
    ci = ci.astype(np.int32)
    for c in range(m):
        ndup = C // 4
        slots = rng.choice(C, size=ndup, replace=False)
        rows = rng.choice(L, size=ndup, replace=False)
        ci[slots, c] = bi[rows, c]
    bd = bi.astype(np.float32)
    cd = ci.astype(np.float32)

    # sort the buffer first (kernel precondition: buffer arrives sorted)
    order = np.argsort(bd, axis=0, kind="stable")
    bd = np.take_along_axis(bd, order, axis=0)
    bi = np.take_along_axis(bi, order, axis=0)
    be = np.take_along_axis(be, order, axis=0)

    od, oi, oe, par = jax.jit(
        lambda a, b, c, e, f: beam_merge_step(
            a, b, c, cand_d=e, cand_i=f, width=width, g=128,
            interpret=True,
        )
    )(jnp.asarray(bd), jnp.asarray(bi), jnp.asarray(be),
      jnp.asarray(cd), jnp.asarray(ci))

    wd, wi, we, wpar = _np_merge_oracle(bd, bi, be, cd, ci, L, width)
    np.testing.assert_array_equal(np.asarray(oi), wi)
    np.testing.assert_allclose(np.asarray(od), wd, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(par), wpar)
    np.testing.assert_array_equal(np.asarray(oe), we)


def test_packed_scoring_matches_direct():
    """In-kernel word decode + scoring == direct int8 math."""
    rng = np.random.default_rng(5)
    n, d, deg, m, width = 512, 32, 8, 128, 2
    L = 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    graph = rng.integers(0, n, (n, deg)).astype(np.int32)
    idx = cagra.from_graph(x, graph, DistanceType.L2Expanded)
    assert idx.nbr_pack is not None

    q = rng.normal(size=(m, d)).astype(np.float32)
    scale = idx.code_scale
    qs = (q * 2.0 * scale).astype(jnp.bfloat16)
    dq = d // 4
    qperm = jnp.transpose(jnp.asarray(qs).reshape(m, dq, 4), (0, 2, 1))
    qrep = jnp.tile(qperm, (1, 1, deg))                  # [m, 4, deg*dq]

    parents = jnp.asarray(
        rng.integers(0, n, (width, m)).astype(np.int32))
    pack = idx.nbr_pack[jnp.maximum(parents.T, 0)]      # [m, width, W]

    bd = jnp.full((L, m), jnp.inf, jnp.float32)
    bi = jnp.full((L, m), -1, jnp.int32)
    be = jnp.zeros((L, m), jnp.int32)
    od, oi, oe, par = beam_merge_step(
        bd, bi, be, qrep=qrep, pack=pack, parents=parents,
        deg=deg, d=d, width=width, g=128, interpret=True,
    )

    # direct scoring oracle (same int8 codes, f32 math, bf16 rounding
    # tolerance)
    codes = np.asarray(idx.flat_codes).astype(np.float32)
    norms = (x.astype(np.float32) ** 2).sum(1)
    pT = np.asarray(parents).T
    nbrs = np.asarray(graph)[np.maximum(pT, 0)].reshape(m, width * deg)
    dots = np.einsum(
        "mcd,md->mc",
        codes[nbrs],
        np.asarray(qs, dtype=np.float32),
    )
    want = norms[nbrs] - dots                          # [m, C]
    got_i = np.asarray(oi)
    got_d = np.asarray(od)
    # every buffer entry must equal the oracle distance of its id
    for c in range(m):
        id2want = {}
        for j, nb in enumerate(nbrs[c]):
            id2want.setdefault(int(nb), want[c, j])
        for t in range(L):
            if got_i[t, c] < 0:
                continue
            w = id2want[int(got_i[t, c])]
            assert abs(got_d[t, c] - w) <= 0.02 * max(1.0, abs(w)), (
                t, c, got_d[t, c], w)


def test_merge_step_tail_queries_padded():
    """m off the g lane tile: the kernel pads inert columns and slices
    them back — outputs must equal the same columns of a pre-padded
    call (the old caller contract) and the numpy oracle."""
    rng = np.random.default_rng(7)
    L, C, m, width = 16, 32, 100, 4
    bi = rng.permutation(np.arange(0, 4 * (L + C) * m))[: L * m]
    bi = bi.reshape(L, m).astype(np.int32)
    be = (rng.random((L, m)) < 0.5).astype(np.int32)
    ci = rng.permutation(
        np.arange(4 * (L + C) * m, 8 * (L + C) * m))[: C * m]
    ci = ci.reshape(C, m).astype(np.int32)
    bd = bi.astype(np.float32)
    cd = ci.astype(np.float32)
    order = np.argsort(bd, axis=0, kind="stable")
    bd = np.take_along_axis(bd, order, axis=0)
    bi = np.take_along_axis(bi, order, axis=0)
    be = np.take_along_axis(be, order, axis=0)

    od, oi, oe, par = beam_merge_step(
        jnp.asarray(bd), jnp.asarray(bi), jnp.asarray(be),
        cand_d=jnp.asarray(cd), cand_i=jnp.asarray(ci),
        width=width, g=128, interpret=True,
    )
    assert oi.shape == (L, m) and par.shape == (width, m)
    wd, wi, we, wpar = _np_merge_oracle(bd, bi, be, cd, ci, L, width)
    np.testing.assert_array_equal(np.asarray(oi), wi)
    np.testing.assert_array_equal(np.asarray(par), wpar)
    np.testing.assert_allclose(np.asarray(od), wd, rtol=1e-6)


def _clustered(rng, n, nq, d=32, n_centers=16):
    centers = rng.uniform(-5, 5, (n_centers, d)).astype(np.float32)
    x = (centers[rng.integers(0, n_centers, n)]
         + 0.7 * rng.standard_normal((n, d))).astype(np.float32)
    q = (centers[rng.integers(0, n_centers, nq)]
         + 0.7 * rng.standard_normal((nq, d))).astype(np.float32)
    return x, q


@pytest.mark.parametrize("metric", [DistanceType.L2Expanded,
                                    DistanceType.InnerProduct])
def test_beam_search_pallas_end_to_end(metric):
    rng = np.random.default_rng(11)
    x, q = _clustered(rng, 4000, 100)
    k = 10
    idx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16, metric=metric), x)
    sp = cagra.SearchParams(itopk_size=64, scan_impl="pallas_interpret")
    d_p, i_p = cagra.search(sp, idx, q, k)
    oracle_metric = ("inner_product" if metric == DistanceType.InnerProduct
                     else "sqeuclidean")
    _, want = naive_knn(q, x, k, metric=oracle_metric)
    assert eval_recall(np.asarray(i_p), want) > 0.9
    # row invariants: unique live ids, sorted distances
    ip = np.asarray(i_p)
    dp = np.asarray(d_p)
    for r in range(ip.shape[0]):
        live = ip[r][ip[r] >= 0]
        assert len(set(live.tolist())) == len(live)
    fin = np.isfinite(dp)
    rowdiff = np.diff(dp, axis=1)
    if metric == DistanceType.InnerProduct:
        rowdiff = -rowdiff
    assert np.all(rowdiff[fin[:, 1:] & fin[:, :-1]] >= -1e-4)


def test_beam_search_pallas_vs_xla_agree():
    rng = np.random.default_rng(12)
    x, q = _clustered(rng, 4000, 100)
    k = 10
    idx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16), x)
    d_p, i_p = cagra.search(
        cagra.SearchParams(scan_impl="pallas_interpret"), idx, q, k)
    d_x, i_x = cagra.search(
        cagra.SearchParams(scan_impl="xla"), idx, q, k)
    _, want = naive_knn(q, x, k)
    rp = eval_recall(np.asarray(i_p), want)
    rx = eval_recall(np.asarray(i_x), want)
    assert rp > 0.9 and rx > 0.9
    # distances are exact (final f32 rescore) on both paths
    both = np.asarray(i_p) == np.asarray(i_x)
    np.testing.assert_allclose(np.asarray(d_p)[both], np.asarray(d_x)[both],
                               rtol=1e-4, atol=1e-4)
