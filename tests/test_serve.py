"""graft-serve tests (ISSUE 5, marker ``serve``).

Covers the three acceptance criteria — post-warmup trace stability
under a mixed-size stream (the GL007 trace-counting hook), loss-free
hot-swap under concurrent load (every request completes, each from
exactly one generation), and tombstone correctness against fresh
indexes across all four index types — plus the micro-batcher unit
surface (ladder, coalescing, padding, backpressure), the resilience
wiring (injected OOM → bucket-ceiling downshift + split; injected
transient → retried), upsert/side-buffer/compaction behavior,
user-prefilter composition, and generation refcount draining."""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from raft_tpu import serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.neighbors.common import BitsetFilter
from raft_tpu.resilience import faultinject
from raft_tpu.serve.batcher import bucket_ladder, choose_bucket, pad_rows

pytestmark = [pytest.mark.serve, pytest.mark.threadsan]

N, DIM = 320, 16


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    # ISSUE 7: the whole serve suite runs with SANITIZED locks — every
    # Server/batcher/registry/mutation lock constructed in these tests
    # goes through analysis/lockwatch, so each run doubles as the
    # zero-inversion / zero-hold-budget-breach acceptance
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    faultinject.clear()
    yield
    faultinject.clear()
    # drop any serve_batch_rows OOM budget a test recorded — it would
    # clamp every later server's starting ceiling
    tuning.reload()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    x = rng.standard_normal((N, DIM)).astype(np.float32)
    q = rng.standard_normal((24, DIM)).astype(np.float32)
    return x, q


def _params(**kw):
    kw.setdefault("max_batch_rows", 16)
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("max_k", 8)
    return serve.ServeParams(**kw)


# ---------------------------------------------------------------------------
# bucket ladder / batcher units
# ---------------------------------------------------------------------------


def test_bucket_ladder_shape():
    assert bucket_ladder(256) == (1, 2, 4, 8, 16, 32, 64, 128, 256)
    assert bucket_ladder(100)[-1] == 128          # rounded up to pow2
    assert bucket_ladder(1) == (1,)


def test_choose_bucket_fallback_and_ceiling():
    lad = bucket_ladder(64)
    assert choose_bucket(lad, 5) == 8
    assert choose_bucket(lad, 64) == 64
    assert choose_bucket(lad, 9, ceiling=8) == 16    # head bigger than cap
    assert choose_bucket(lad, 3, ceiling=8) == 4


def test_pad_rows_host_only():
    q = np.ones((3, 4), np.float32)
    out = pad_rows(q, 8)
    assert out.shape == (8, 4) and (out[3:] == 0).all()
    assert pad_rows(q, 3) is q


def test_submit_result_matches_oracle(data):
    x, q = data
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x)
        d, i = srv.search(q[:5], 4)
        gd, gi = brute_force.knn(q[:5], x, 4)
        np.testing.assert_array_equal(i, np.asarray(gi))
        np.testing.assert_array_equal(d, np.asarray(gd))


def test_concurrent_submits_coalesce_and_match(data):
    x, q = data
    gd, gi = brute_force.knn(q, x, 4)
    gi = np.asarray(gi)
    with serve.Server(_params(max_wait_ms=5.0, warmup=False)) as srv:
        srv.create_index("default", x)
        futs = [srv.submit(q[j], 4) for j in range(q.shape[0])]
        for j, f in enumerate(futs):
            _, ids = f.result(timeout=60)
            np.testing.assert_array_equal(ids[0], gi[j])


def test_mixed_k_requests(data):
    x, q = data
    with serve.Server(_params(max_wait_ms=5.0, warmup=False)) as srv:
        srv.create_index("default", x)
        ks = [1, 3, 5, 8, 2, 7]
        futs = [srv.submit(q[j], k) for j, k in enumerate(ks)]
        for j, (f, k) in enumerate(zip(futs, ks)):
            d, ids = f.result(timeout=60)
            assert ids.shape == (1, k)
            _, gi = brute_force.knn(q[j:j + 1], x, k)
            np.testing.assert_array_equal(ids, np.asarray(gi))


def test_non_pow2_max_k_warm_and_served(data):
    x, q = data
    with serve.Server(_params(max_k=10)) as srv:   # warmup on
        srv.create_index("default", x)
        # the k-ladder tops at max_k itself, not the last pow2 below it:
        # submit admits any k <= max_k, so k in (8, 10] must be servable
        # (and warmed — the max_k rung is part of the traced ladder)
        ks = (9, 10, 5)
        # oracle traces its own (unpadded) shapes: keep it out of the
        # serve-side trace-stability window
        oracle = {k: np.asarray(brute_force.knn(q[:3], x, k)[1])
                  for k in ks}
        before = serve.trace_cache_sizes()
        for k in ks:
            _, i = srv.search(q[:3], k)
            assert i.shape == (3, k)
            np.testing.assert_array_equal(i, oracle[k])
        assert serve.trace_cache_sizes() == before


def test_rabitq_rung_serves_trace_stable(data):
    """ISSUE 11: the rabitq multi-stage pipeline is reachable from serve
    (ivf_pq index with a rabitq cache routes through search_refined,
    tombstones composing with the first stage) and steady-state serving
    adds ZERO XLA traces — the warmup ladder covers both pipeline
    stages."""
    from raft_tpu.neighbors import ivf_pq

    x, q = data
    bp = ivf_pq.IndexParams(n_lists=8, pq_dim=16, kmeans_n_iters=4,
                            cache_dtype="rabitq")
    with serve.Server(_params(max_k=8)) as srv:    # warmup on
        srv.create_index("default", x, algo="ivf_pq", build_params=bp,
                         search_params=ivf_pq.SearchParams(n_probes=8))
        before = serve.trace_cache_sizes()
        d, i = srv.search(q[:5], 4)
        assert i.shape == (5, 4)
        assert (np.asarray(i) >= 0).all()
        # delete a served id: the tombstone must compose with the FIRST
        # stage (the deleted row never reaches the rerank shortlist)
        victim = int(np.asarray(i)[0, 0])
        srv.delete([victim])
        _, i2 = srv.search(q[:5], 4)
        assert victim not in np.asarray(i2)
        assert serve.trace_cache_sizes() == before


def test_submit_validation(data):
    x, _ = data
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x)
        with pytest.raises(ValueError, match="max_k"):
            srv.submit(x[0], 99)
        with pytest.raises(ValueError, match="max_batch_rows"):
            srv.submit(x[:17], 4)       # > max_batch_rows in one request
        with pytest.raises(ValueError, match="dim"):
            # rejected at the door: coalesced into a batch it would fail
            # every other request at dispatch
            srv.submit(x[0, :-1], 4)
        with pytest.raises(KeyError):
            srv.submit(x[0], 4, index="nope")


def test_overload_rejection_is_transient(data):
    from raft_tpu import resilience

    x, _ = data
    srv = serve.Server(_params(max_queue_rows=2, max_wait_ms=200.0))
    try:
        srv.create_index("default", x, warmup=False)
        futs, rejected = [], None
        for j in range(6):
            try:
                futs.append(srv.submit(x[j], 2))
            except serve.Overloaded as e:
                rejected = e
                break
        assert rejected is not None, "bounded queue never pushed back"
        assert resilience.classify(rejected) == resilience.TRANSIENT
        for f in futs:                       # admitted work still completes
            f.result(timeout=60)
    finally:
        srv.close()


def test_closed_rejection_is_fatal(data):
    from raft_tpu import resilience

    x, _ = data
    srv = serve.Server(_params())
    srv.create_index("default", x, warmup=False)
    srv.close()
    with pytest.raises(serve.Overloaded) as ei:
        srv.submit(x[0], 2)
    # a closed server can never accept again: the rejection must fail
    # fast, not carry the backoff-and-retry advice queue_full does
    assert ei.value.reason == "closed"
    assert resilience.classify(ei.value) == resilience.FATAL
    # mutation/warmup entry points get the same truthful diagnosis, not
    # a KeyError claiming the index was never published
    for call in (lambda: srv.delete([1]),
                 lambda: srv.upsert(x[0], [9000]),
                 lambda: srv.warmup()):
        with pytest.raises(RuntimeError, match="server is closed"):
            call()


def test_submit_before_first_publish_rejected_not_ready(
        data, monkeypatch):
    # create_index registers the serving BEFORE its first publish, and
    # warmup can hold that window open for minutes — a submit landing in
    # it must get a retryable not_ready rejection, not an enqueue whose
    # future later fails with the dispatcher's internal KeyError
    from raft_tpu import resilience

    x, _ = data
    srv = serve.Server(_params())
    installed, gate = threading.Event(), threading.Event()
    real_publish = serve.Server._publish_guarded

    def held_publish(self, name, h):
        installed.set()
        assert gate.wait(timeout=30), "test gate never released"
        return real_publish(self, name, h)

    monkeypatch.setattr(serve.Server, "_publish_guarded", held_publish)
    t = threading.Thread(
        target=lambda: srv.create_index("default", x, warmup=False))
    t.start()
    try:
        assert installed.wait(timeout=30)
        with pytest.raises(serve.Overloaded) as ei:
            srv.submit(x[0], 2)
        assert ei.value.reason == "not_ready"
        assert resilience.classify(ei.value) == resilience.TRANSIENT
    finally:
        gate.set()
        t.join(timeout=30)
    # once the first generation publishes, the same call serves
    d, i = srv.search(x[0], 2)
    assert int(i[0, 0]) == 0
    srv.close()


# ---------------------------------------------------------------------------
# acceptance: trace stability (GL007 hook)
# ---------------------------------------------------------------------------


def test_steady_state_adds_zero_traces(data):
    x, q = data
    rng = np.random.default_rng(7)
    with serve.Server(_params(max_wait_ms=0.5)) as srv:
        srv.create_index("default", x)
        # tombstones + a user filter exercise the filtered paths too
        srv.delete([1, 2, 3])
        filt = Bitset.from_dense(np.arange(N) % 2 == 0)
        srv.search(q[:3], 4, prefilter=filt)
        before = serve.trace_cache_sizes()
        for rows in (1, 3, 7, 2, 11, 16, 5, 1, 9, 13):
            block = rng.standard_normal((rows, DIM)).astype(np.float32)
            for k in (1, 3, 5, 8):
                srv.search(block, k)
        srv.search(q[:5], 4, prefilter=filt)
        srv.delete([9])                      # mutation between batches
        srv.search(q[:2], 3)
        after = serve.trace_cache_sizes()
        assert after == before, (
            f"steady-state serving retraced: {before} -> {after}")
        # upserts advance next_int, which feeds every kernel's STATIC
        # filter_nbits: the pow2 capacity rung (+ re-warm when it or the
        # side buffer grows) must keep serving trace-stable rather than
        # retracing on every single upsert
        srv.upsert(rng.standard_normal(DIM).astype(np.float32), [N + 1])
        before = serve.trace_cache_sizes()
        for rows in (2, 5, 1, 8):
            block = rng.standard_normal((rows, DIM)).astype(np.float32)
            srv.search(block, 4)
        # same capacity rung: no shape changed, so no re-warm happened
        srv.upsert(rng.standard_normal(DIM).astype(np.float32), [N + 2])
        srv.search(q[:3], 4, prefilter=filt)
        srv.search(q[:2], 3)
        after = serve.trace_cache_sizes()
        assert after == before, (
            f"post-upsert serving retraced: {before} -> {after}")


# ---------------------------------------------------------------------------
# acceptance: loss-free hot swap under load
# ---------------------------------------------------------------------------


def test_hot_swap_loss_free_under_load(data):
    x, q = data
    x2 = (x[::-1] * 1.5).copy()              # different content, same shape
    k = 4
    exp = {1: np.asarray(brute_force.knn(q, x, k)[1]),
           2: np.asarray(brute_force.knn(q, x2, k)[1])}
    with serve.Server(_params(max_wait_ms=0.5, warmup=False)) as srv:
        srv.create_index("default", x)
        gen1 = srv.registry.get("default")
        stop = threading.Event()
        results, errors = [], []

        def worker(wid):
            wrng = np.random.default_rng(wid)
            while not stop.is_set():
                j = int(wrng.integers(q.shape[0]))
                f = srv.submit(q[j], k)
                try:
                    _, ids = f.result(timeout=60)
                except Exception as e:  # noqa: BLE001 — the assertion below reports it
                    errors.append(e)
                    return
                results.append((j, f.generation, ids[0].copy()))

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        fut = srv.swap("default", dataset=x2)
        assert fut.result(timeout=300) == 2
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert not errors, errors
        assert results, "no requests completed"
        gens = {g for _, g, _ in results}
        assert gens <= {1, 2} and 2 in gens
        # every answer comes from exactly ONE generation: it matches that
        # generation's expected ids bit-for-bit, never a mixture
        for j, g, ids in results:
            np.testing.assert_array_equal(ids, exp[g][j])
        # the retired generation drains once its pins are gone
        assert gen1.drained.wait(timeout=30)
        assert gen1.handle is None


def test_generation_refcount_drain(data):
    x, _ = data
    with serve.Server(_params()) as srv:
        srv.create_index("default", x, warmup=False)
        g1 = srv.registry.pin("default")          # simulated in-flight batch
        srv.swap("default", dataset=x, wait=True)
        assert srv.generation() == 2
        assert not g1.drained.is_set(), "drained while still pinned"
        g1.release()
        assert g1.drained.wait(timeout=10)


def test_swap_rederives_default_search_params(data):
    # default ivf search params (n_probes = n_lists, the exhaustive-
    # probing serving contract) must be re-derived against the NEW
    # index on swap — inheriting the old resolved params would clamp
    # probing at the old index's n_lists and silently serve
    # non-exhaustive results on a bigger successor
    x, _ = data
    rng = np.random.default_rng(11)
    big = rng.standard_normal((N * 4, DIM)).astype(np.float32)
    with serve.Server(_params()) as srv:
        srv.create_index("default", x, algo="ivf_flat", warmup=False)
        h0 = srv.registry.get("default").handle
        assert h0.search_params.n_probes == h0.index.n_lists
        srv.swap("default", dataset=big, wait=True)
        h1 = srv.registry.get("default").handle
        assert h1.index.n_lists > h0.index.n_lists
        assert h1.search_params.n_probes == h1.index.n_lists
        # explicit user params still stick across a swap
        srv.swap("default", dataset=x,
                 search_params=ivf_flat.SearchParams(n_probes=3),
                 wait=True)
        srv.swap("default", dataset=big, wait=True)
        h3 = srv.registry.get("default").handle
        assert h3.search_params.n_probes == 3


def test_warmup_oom_downshifts_instead_of_failing(data, monkeypatch):
    # a device OOM tracing the top warmup bucket must downshift the
    # ladder (like the dispatch path's OOM ladder) and bring the server
    # up serving the buckets that fit — not abort create_index
    from raft_tpu.serve import engine as _eng

    x, q = data
    real = _eng._IndexServing._run_search

    def oom_above_4(self, h, batch, *a, **kw):
        if batch.bucket >= 8:
            raise RuntimeError("RESOURCE_EXHAUSTED: warmup shape too big")
        return real(self, h, batch, *a, **kw)

    monkeypatch.setattr(_eng._IndexServing, "_run_search", oom_above_4)
    with serve.Server(_params()) as srv:
        srv.create_index("default", x)            # warmup on: must survive
        assert srv._serving("default").batcher.ceiling == 4
        d, i = srv.search(q[:2], 3)
        _, gi = brute_force.knn(q[:2], x, 3)
        np.testing.assert_array_equal(i, np.asarray(gi))


def test_load_index_publishes_snapshot(tmp_path, data):
    x, q = data
    idx = brute_force.build(x)
    path = str(tmp_path / "bf.idx")
    brute_force.save(path, idx)
    with serve.Server(_params()) as srv:
        srv.load_index("default", path, algo="brute_force", warmup=False)
        d, i = srv.search(q[:3], 4)
        _, gi = brute_force.knn(q[:3], x, 4)
        np.testing.assert_array_equal(i, np.asarray(gi))


# ---------------------------------------------------------------------------
# acceptance: tombstone correctness across all four index types
# ---------------------------------------------------------------------------


def _fresh_and_served(algo, x, q, k, dead, params=None, **kw):
    """Serve x with `dead` deleted vs the same algo freshly built on the
    survivors; returns (served (d, i-as-original-ids), fresh mapped to
    original ids)."""
    surv = np.setdiff1d(np.arange(x.shape[0]), dead)
    xs = x[surv]
    params = params or _params(max_wait_ms=0.5, warmup=False)
    with serve.Server(params) as srv:
        srv.create_index("default", x, algo=algo, **kw)
        srv.delete(dead)
        sd, si = srv.search(q, k)
    with serve.Server(params) as srv:
        srv.create_index("default", xs, algo=algo, **kw)
        fd, fi = srv.search(q, k)
    fi = np.where(fi >= 0, surv[np.clip(fi, 0, surv.size - 1)], -1)
    return (sd, si), (fd, fi)


@pytest.mark.parametrize("algo,kw", [
    ("brute_force", {}),
    ("ivf_flat", {}),
    ("ivf_pq", {"refine_ratio": 4}),
])
def test_tombstone_matches_fresh_index(data, algo, kw):
    x, q = data
    dead = np.asarray([0, 5, 17, 42, 99, 123, 200, 319])
    (sd, si), (fd, fi) = _fresh_and_served(algo, x, q[:8], 5, dead, **kw)
    assert not np.isin(si, dead).any()
    np.testing.assert_array_equal(si, fi)
    np.testing.assert_array_equal(sd, fd)


@pytest.mark.slow
def test_tombstone_matches_fresh_index_cagra(data):
    """The cagra leg of the tombstone matrix. Graph build + beam-search
    compiles dominate (~3 min on the CPU host even at a reduced set /
    small ladder — dated 2026-08-03, this suite), so like the rest of
    the cagra build tests it rides the full suite's slow lane; tier-1
    covers brute_force/ivf_flat/ivf_pq above."""
    x, q = data
    x = x[:160]
    dead = np.asarray([0, 5, 17, 42, 99, 123])
    bp = cagra.IndexParams(intermediate_graph_degree=32, graph_degree=16)
    (sd, si), (fd, fi) = _fresh_and_served(
        "cagra", x, q[:4], 5, dead,
        params=_params(max_batch_rows=4, max_wait_ms=0.5),
        build_params=bp)
    assert not np.isin(si, dead).any()
    np.testing.assert_array_equal(si, fi)
    np.testing.assert_array_equal(sd, fd)


def test_tombstones_never_leak_when_live_lt_k(data):
    rng = np.random.default_rng(12)
    small = rng.standard_normal((8, DIM)).astype(np.float32)
    with serve.Server(_params(warmup=False, compact_threshold=0)) as srv:
        srv.create_index("default", small)
        # fewer live rows than k: the tombstoned rows ride top-k at the
        # sentinel distance with their REAL ids inside the kernel — the
        # engine must mask them to -1, never hand a deleted id back
        srv.delete([0, 1, 2, 3, 4, 5])
        _, i = srv.search(small[6], 4)
        assert set(i[0].tolist()) == {6, 7, -1}
        assert (i[0] == -1).sum() == 2
        # same through the side buffer: deleted side-resident slots keep
        # their internal ids at the sentinel inside _merge_with_side
        vs = rng.standard_normal((3, DIM)).astype(np.float32)
        srv.upsert(vs, [100, 101, 102])
        srv.delete([101, 102])
        _, i2 = srv.search(small[7], 5)
        assert set(i2[0].tolist()) == {6, 7, 100, -1}
        assert (i2[0] == -1).sum() == 2


def test_delete_is_idempotent_and_counted(data):
    x, _ = data
    with serve.Server(_params()) as srv:
        srv.create_index("default", x, warmup=False)
        assert srv.delete([1, 2, 3]) == 3
        assert srv.delete([2, 3, 4]) == 1          # only 4 newly dead
        assert srv.stats()["tombstoned_rows"] == 4


def test_delete_stays_dead_across_upsert_transition(data):
    """An id deleted in identity mode must not be resurrected when the
    first upsert installs the explicit id translation (review fix)."""
    x, _ = data
    rng = np.random.default_rng(9)
    with serve.Server(_params(compact_threshold=0, warmup=False)) as srv:
        srv.create_index("default", x, warmup=False)
        assert srv.delete([5]) == 1
        srv.upsert(rng.standard_normal(DIM).astype(np.float32), [7777])
        assert srv.delete([5]) == 0                # still dead, not live
        _, i = srv.search(x[5], 5)
        assert 5 not in i


def test_k_beyond_index_rows_rejected():
    rng = np.random.default_rng(10)
    small = rng.standard_normal((6, DIM)).astype(np.float32)
    with serve.Server(_params(max_k=8)) as srv:
        srv.create_index("default", small, warmup=False)
        with pytest.raises(ValueError, match="index rows"):
            srv.submit(small[0], 7)                # 7 > 6 rows
        _, i = srv.search(small[0], 6)             # k == rows is fine
        assert i.shape == (1, 6)


# ---------------------------------------------------------------------------
# upsert / side buffer / compaction
# ---------------------------------------------------------------------------


def test_upsert_reachable_before_compaction(data):
    x, q = data
    rng = np.random.default_rng(3)
    with serve.Server(_params(compact_threshold=0, warmup=False)) as srv:
        srv.create_index("default", x)
        v = rng.standard_normal(DIM).astype(np.float32)
        srv.upsert(v, [7777])
        d, i = srv.search(v, 3)
        assert i[0, 0] == 7777 and d[0, 0] == pytest.approx(0.0, abs=1e-4)
        assert srv.generation() == 1               # no swap happened
        # replacement: upserting an EXISTING id hides the old row
        srv.upsert(v + 1.0, [0])
        d2, i2 = srv.search(v + 1.0, 1)
        assert i2[0, 0] == 0 and d2[0, 0] == pytest.approx(0.0, abs=1e-4)
        # a brand-new id can be deleted again while still side-resident
        srv.upsert(v + 2.0, [8888])
        srv.delete([8888])
        _, i3 = srv.search(v + 2.0, 5)
        assert 8888 not in i3


def test_base_delete_keeps_side_index_cache(data):
    x, _ = data
    with serve.Server(_params(compact_threshold=0, warmup=False)) as srv:
        srv.create_index("default", x)
        v = np.ones(DIM, np.float32)
        srv.upsert(v, [7000])
        srv.search(v, 2)                       # builds the side cache
        h = srv.registry.get("default").handle
        cached = h._side_cache
        assert cached is not None
        srv.delete([5])                        # tombstones a BASE row only
        _, i = srv.search(v, 2)
        assert i[0, 0] == 7000
        assert h._side_cache is cached, (
            "a base-row delete must not rebuild the side brute-force "
            "index — its content did not change")
        srv.upsert(v + 1.0, [7001])            # side content DID change
        srv.search(v, 2)
        assert h._side_cache is not cached


def test_per_index_warmup_override_respected(data, monkeypatch):
    from raft_tpu.serve import engine as serve_engine

    x, _ = data
    calls = []
    monkeypatch.setattr(
        serve_engine._IndexServing, "warmup_handle",
        lambda self, h: calls.append(self.name) or 0)
    # server-wide warmup stays True: the per-call override at
    # create_index must be remembered and gate the implicit re-warms
    # (growing upsert, compaction, swap) too
    with serve.Server(_params(side_capacity=1, compact_threshold=0)) as srv:
        srv.create_index("default", x, warmup=False)
        assert calls == []
        srv.upsert(np.ones(DIM, np.float32), [9000])       # side alloc
        srv.upsert(np.ones(DIM, np.float32) * 2, [9001])   # side grows
        assert calls == [], "warmup=False index re-warmed on upsert"
        srv.swap("default", dataset=x, wait=True)
        assert calls == [], "warmup=False index re-warmed on swap"


def test_compaction_extends_and_swaps(data):
    x, q = data
    rng = np.random.default_rng(4)
    with serve.Server(_params(compact_threshold=0, warmup=False)) as srv:
        srv.create_index("default", x, algo="ivf_flat")
        vecs = rng.standard_normal((3, DIM)).astype(np.float32)
        ids = [9001, 9002, 9003]
        srv.upsert(vecs, ids)
        assert srv.stats()["side_rows"] == 3
        fut = srv.compact(wait=True)
        assert fut.result() == 2                   # one swap
        assert srv.stats()["side_rows"] == 0
        for v, e in zip(vecs, ids):                # now served from main
            _, i = srv.search(v, 1)
            assert i[0, 0] == e
        # deletes recorded before compaction stay deleted after
        srv.delete([9002])
        _, i = srv.search(vecs[1], 3)
        assert 9002 not in i


def test_auto_compaction_at_threshold(data):
    x, _ = data
    rng = np.random.default_rng(5)
    with serve.Server(_params(compact_threshold=4, side_capacity=4,
                              warmup=False)) as srv:
        srv.create_index("default", x)
        for j in range(4):
            srv.upsert(rng.standard_normal(DIM).astype(np.float32),
                       [5000 + j])
        deadline = time.monotonic() + 120
        while srv.generation() < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.generation() >= 2, "auto-compaction never swapped"
        _, i = srv.search(x[:2], 4)               # still serving correctly
        assert (i >= 0).all()


# ---------------------------------------------------------------------------
# prefilter composition
# ---------------------------------------------------------------------------


def test_user_prefilter_composes_with_tombstones(data):
    x, q = data
    allowed = np.arange(N) % 3 != 0
    dead = np.asarray([1, 2, 4, 5, 7, 8])          # all pass the filter?
    dead = dead[allowed[dead]]
    filt = Bitset.from_dense(allowed)
    with serve.Server(_params(max_wait_ms=2.0, warmup=False)) as srv:
        srv.create_index("default", x)
        srv.delete(dead)
        futs = [srv.submit(q[j], 5, prefilter=filt) for j in range(6)]
        eff = allowed.copy()
        eff[dead] = False
        sub = np.where(eff)[0]
        _, gi = brute_force.knn(q[:6], x[sub], 5)
        want = sub[np.asarray(gi)]
        for j, f in enumerate(futs):
            _, ids = f.result(timeout=60)
            np.testing.assert_array_equal(ids[0], want[j])


def test_user_prefilter_mutated_in_place_not_served_stale(data):
    """Bitset's public API mutates in place; the composed-filter device
    cache must key on content (via Bitset._version), not identity alone,
    or the second search serves rows the caller just excluded."""
    x, q = data
    filt = Bitset.from_dense(np.ones(N, dtype=bool))
    with serve.Server(_params(max_wait_ms=1.0, warmup=False)) as srv:
        srv.create_index("default", x)
        _, ids0 = srv.search(q[0], 5, prefilter=filt)
        banned = ids0[0].astype(np.int64)
        filt.set(np.asarray(banned), False)        # in-place mutation
        _, ids1 = srv.search(q[0], 5, prefilter=filt)
        assert not np.intersect1d(ids1[0], banned).size, (
            "stale composed filter served excluded rows")


def test_mixed_filter_traffic_splits_batches(data):
    x, q = data
    f1 = Bitset.from_dense(np.arange(N) < 200)
    with serve.Server(_params(max_wait_ms=5.0, warmup=False)) as srv:
        srv.create_index("default", x)
        futs = [srv.submit(q[0], 4),
                srv.submit(q[1], 4, prefilter=f1),
                srv.submit(q[2], 4)]
        _, i0 = futs[0].result(timeout=60)
        _, i1 = futs[1].result(timeout=60)
        _, i2 = futs[2].result(timeout=60)
        assert (i1 < 200).all()
        _, g0 = brute_force.knn(q[:1], x, 4)
        np.testing.assert_array_equal(i0, np.asarray(g0))


# ---------------------------------------------------------------------------
# resilience wiring
# ---------------------------------------------------------------------------


def test_injected_oom_downshifts_and_splits(data):
    x, q = data
    with serve.Server(_params(max_wait_ms=50.0, warmup=False)) as srv:
        srv.create_index("default", x)
        assert srv.stats()["bucket_ceiling"] == 16
        faultinject.install("oom@stage:serve.dispatch")
        futs = [srv.submit(q[2 * j:2 * j + 2], 4) for j in range(4)]
        _, gi = brute_force.knn(q[:8], x, 4)
        gi = np.asarray(gi)
        for j, f in enumerate(futs):               # every request answered
            _, ids = f.result(timeout=120)
            np.testing.assert_array_equal(ids, gi[2 * j:2 * j + 2])
        assert srv.stats()["bucket_ceiling"] < 16
        assert tuning.runtime_budget("serve_batch_rows") is not None


def test_injected_transient_is_retried(data):
    x, q = data
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x)
        faultinject.install("transient@stage:serve.dispatch")
        d, i = srv.search(q[:2], 4)
        _, gi = brute_force.knn(q[:2], x, 4)
        np.testing.assert_array_equal(i, np.asarray(gi))


def test_single_request_oom_fails_cleanly(data):
    x, q = data
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x)
        faultinject.install("oom@stage:serve.dispatch*99")
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            srv.search(q[0], 4)
        faultinject.clear()
        _, i = srv.search(q[0], 4)                 # server still healthy
        _, gi = brute_force.knn(q[:1], x, 4)
        np.testing.assert_array_equal(i, np.asarray(gi))


# ---------------------------------------------------------------------------
# observability wiring
# ---------------------------------------------------------------------------


def test_serve_metrics_emitted(data):
    from raft_tpu import obs

    x, q = data
    obs.set_mode("on")
    try:
        obs.reset()
        with serve.Server(_params(max_wait_ms=2.0)) as srv:
            srv.create_index("default", x)
            futs = [srv.submit(q[j], 4) for j in range(6)]
            for f in futs:
                f.result(timeout=60)
            srv.delete([3])
            srv.swap("default", dataset=x, wait=True)
        m = obs.snapshot(runtime_gauges=False)["metrics"]
        for name in ("serve.requests_total", "serve.queries_total",
                     "serve.batches_total", "serve.batch_fill_ratio",
                     "serve.batch_latency_ms", "serve.swaps_total",
                     "serve.deletes_total", "serve.warmup_shapes"):
            assert name in m, f"{name} missing from {sorted(m)}"
        assert sum(p["value"] for p in
                   m["serve.swaps_total"]["points"]) >= 2
    finally:
        obs.set_mode(None)
        obs.reset()


# ---------------------------------------------------------------------------
# graft-race regressions (ISSUE 7): races found dogfooding GL010/GL011
# ---------------------------------------------------------------------------


def test_lower_ceiling_is_monotone():
    """The OOM downshift's atomic clamp: a later, SHALLOWER downshift
    must not raise the ceiling back over a deeper one (the old
    read-modify-write through set_ceiling(min(ceiling, x)) could
    interleave and lose the deeper update)."""
    b = serve.MicroBatcher(lambda batch: None, max_batch_rows=64)
    try:
        assert b.lower_ceiling(8) == 8
        # shallower clamp afterwards: must stay at 8, never go back up
        assert b.lower_ceiling(32) == 8
        assert b.ceiling == 8
        # floor is the smallest ladder rung
        assert b.lower_ceiling(0) == b.ladder[0]
        # set_ceiling remains the explicit (non-monotone) knob
        b.set_ceiling(32)
        assert b.ceiling == 32
    finally:
        b.close(timeout_s=10)


def test_add_on_drain_during_drain_still_fires():
    """A callback registered while _drain is mid-flight (captured its
    list, not yet drained.set()) must still be invoked — it used to be
    appended to a list nobody would ever read again (for the fabric:
    _retire_cluster never fired and workers pinned retired shards)."""
    from raft_tpu.serve.registry import Generation

    gen = Generation("g", 1, handle=object())
    fired = []

    def first(g):
        # runs inside _drain's callback loop: drained is NOT yet set,
        # the capture already happened — the pre-fix window
        assert not g.drained.is_set()
        g.add_on_drain(lambda g2: fired.append("late"))

    gen.add_on_drain(first)
    gen.retire()                      # no pins -> drains inline
    assert gen.drained.is_set()
    assert fired == ["late"], fired


def test_serve_trace_waterfalls_share_batch_span_link(data):
    """graft-trace on the single-process path (ISSUE 13): each submit
    mints a trace at the serving entry; two requests coalesced into ONE
    batch complete as two waterfalls (queue_wait + batch_search) whose
    batch stages carry the SAME batch_seq — the span link tying the
    traces one dispatch served."""
    from raft_tpu import obs

    dataset, queries = data
    obs.set_mode("on")
    try:
        srv = serve.Server(serve.ServeParams(
            max_batch_rows=16, max_wait_ms=150.0, max_k=8))
        srv.create_index("default", dataset, algo="brute_force")
        obs.trace.reset()                 # drop warmup-era records
        f1 = srv.submit(queries[:1], 4)
        f2 = srv.submit(queries[1:2], 4)
        f1.result(timeout=30)
        f2.result(timeout=30)
        wfs = obs.trace_report()
        assert len(wfs) == 2
        seqs = set()
        for wf in wfs:
            assert wf["entry"] == "serve.submit"
            assert wf["status"] == "ok"
            names = [s["stage"] for s in wf["stages"]]
            assert names == ["queue_wait", "batch_search"]
            batch = wf["stages"][1]
            assert batch["bucket"] >= 2 and "linger_ms" in batch
            seqs.add(batch["batch_seq"])
        assert len(seqs) == 1             # one batch served both traces
        # a rejected submit still completes a (tiny) waterfall saying why
        srv.close()
        with pytest.raises(serve.Overloaded):
            srv.submit(queries[:1], 4)
        tail = obs.trace_report()[-1]
        assert tail["status"] == "rejected"
        assert tail["attrs"]["reason"] == "closed"
    finally:
        obs.set_mode(None)
        obs.reset()


def test_threadsan_suite_verdict_zzz():
    """Suite-level ISSUE-7 acceptance (runs last in file order): every
    serve test above constructed its locks through the sanitizer, and
    the observed acquisition order stayed acyclic with zero hold-budget
    breaches — an inversion/breach would also have failed its own test
    by raising."""
    from raft_tpu.analysis import lockwatch as lw

    s = lw.stats()
    assert s["inversions"] == 0 and s["budget_breaches"] == 0, s
    # the serve hierarchy actually got exercised: the mutation ->
    # engine -> registry -> generation chain appears in the graph
    g = lw.order_graph()
    assert "serve.registry" in g and "serve.generation" in \
        g["serve.registry"], sorted(g)
