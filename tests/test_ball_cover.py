"""Ball cover + epsilon neighborhood tests (mirrors
cpp/test/neighbors/ball_cover.cu: exactness vs brute force on haversine
and euclidean, eps_nn degree checks)."""

import numpy as np
import pytest

from raft_tpu.neighbors import ball_cover, brute_force, epsilon_neighborhood


def _geo(n, seed):
    """lat/lon in radians, clustered like city data."""
    rng = np.random.default_rng(seed)
    hubs = rng.uniform([-1.0, -2.5], [1.0, 2.5], (12, 2))
    pts = hubs[rng.integers(0, 12, n)] + rng.normal(0, 0.02, (n, 2))
    pts[:, 0] = np.clip(pts[:, 0], -1.4, 1.4)
    return pts.astype(np.float32)


def _recall(got, want):
    return np.mean([
        len(set(got[r]) & set(want[r])) / want.shape[1]
        for r in range(want.shape[0])
    ])


class TestBallCoverEuclidean:
    def test_exact_vs_brute_force(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((5000, 3)).astype(np.float32)
        q = rng.standard_normal((500, 3)).astype(np.float32)
        index = ball_cover.build(x, metric="euclidean")
        d, i = ball_cover.knn_query(index, q, k=10)
        bd, bi = brute_force.knn(q, x, 10, metric="euclidean")
        assert _recall(np.asarray(i), np.asarray(bi)) > 0.999
        np.testing.assert_allclose(
            np.sort(np.asarray(d), 1), np.sort(np.asarray(bd), 1),
            rtol=1e-4, atol=1e-4,
        )

    def test_all_knn_query(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2000, 3)).astype(np.float32)
        index = ball_cover.build(x, metric="euclidean")
        d, i = ball_cover.all_knn_query(index, k=5)
        # each point's own id must be its nearest neighbor (distance 0)
        first = np.asarray(i)[:, 0]
        np.testing.assert_array_equal(first, np.arange(2000))

    def test_rejects_non_true_metric(self):
        with pytest.raises(ValueError):
            ball_cover.build(np.zeros((10, 2), np.float32), metric="cosine")


class TestBallCoverHaversine:
    def test_exact_vs_brute_force_haversine(self):
        x = _geo(4000, seed=2)
        q = _geo(400, seed=3)
        index = ball_cover.build(x, metric="haversine")
        d, i = ball_cover.knn_query(index, q, k=8)
        bd, bi = brute_force.knn(q, x, 8, metric="haversine")
        assert _recall(np.asarray(i), np.asarray(bi)) > 0.999
        np.testing.assert_allclose(
            np.sort(np.asarray(d), 1), np.sort(np.asarray(bd), 1),
            rtol=1e-4, atol=1e-5,
        )


class TestEpsilonNeighborhood:
    def test_adjacency_vs_numpy(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((200, 5)).astype(np.float32)
        y = rng.standard_normal((300, 5)).astype(np.float32)
        eps_sq = 4.0
        adj, vd = epsilon_neighborhood.eps_neighbors_l2sq(x, y, eps_sq)
        d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        want = d <= eps_sq
        np.testing.assert_array_equal(np.asarray(adj), want)
        np.testing.assert_array_equal(np.asarray(vd), want.sum(1))

    def test_ball_cover_eps_nn(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((1000, 3)).astype(np.float32)
        q = rng.standard_normal((100, 3)).astype(np.float32)
        index = ball_cover.build(x, metric="euclidean")
        adj, vd = ball_cover.eps_nn(index, q, eps=1.5)
        d = np.sqrt(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1))
        np.testing.assert_array_equal(np.asarray(adj), d <= 1.5)
