"""Kernel-contract harness (marker: ``kernel_contract``, tier-1).

Every Pallas kernel in ``ops/`` (and the kernel-shaped select_k rungs)
registers a :class:`~raft_tpu.analysis.contracts.KernelContract`; this
module drives each contract's ADVERSARIAL shape sweep in interpret mode
against XLA oracles — non-divisible rows, ``k == n``, ``k == 1``,
single-row batches, sublane-boundary ±1 row counts, lane-boundary k,
every declared dtype (docs/static_analysis.md §engine-4). The same
cases feed the graft-kern static verifier's bindings, so the static
geometry audit and this dynamic sweep cross-check each other; the
on-chip rerun of the same cases lives in ``scripts/tpu_parity.py``.
"""

import numpy as np
import pytest

from raft_tpu.analysis import contracts

pytestmark = pytest.mark.kernel_contract

CONTRACTS = contracts.load_all()


def _all_cases():
    out = []
    for name, c in CONTRACTS.items():
        for i, case in enumerate(contracts.adversarial_cases(c)):
            if case.get("static_only"):
                continue
            label = "-".join(
                f"{k}={case[k]}" for k in ("impl", "variant", "extract",
                                           "dtype", "k", "n", "cap", "L",
                                           "m")
                if k in case and isinstance(case[k], (int, str)))
            out.append(pytest.param(name, case, id=f"{name}-{i}-{label}"))
    return out


@pytest.mark.parametrize("cname, case", _all_cases())
def test_contract_case(cname, case):
    c = CONTRACTS[cname]
    rep = c.resolve_driver()(c, case, interpret=True)
    assert rep.ok, (cname, case, rep)


# ---------------------------------------------------------------------------
# registry + sweep-shape sanity (the cross-check contract)
# ---------------------------------------------------------------------------


def test_every_ops_kernel_has_a_contract():
    """ISSUE 10 acceptance: every kernel module in ops/ registers a
    contract (a new kernel without one fails here, not in review)."""
    modules = {c.module for c in CONTRACTS.values()}
    for mod in ("raft_tpu.ops.fused_topk", "raft_tpu.ops.ivf_scan",
                "raft_tpu.ops.beam_step", "raft_tpu.ops.graph_join",
                "raft_tpu.matrix.select_k"):
        assert mod in modules, f"{mod} has no kernel contract"


def test_sweep_covers_the_adversarial_classes():
    """The generator actually produces the classes the ISSUE names."""
    for name, c in CONTRACTS.items():
        cases = contracts.adversarial_cases(c)
        assert cases, name
        dtypes = {x.get("dtype") for x in cases} - {None}
        assert dtypes >= set(c.dtypes), (name, dtypes)
        if c.k_key:
            ks = {x.get(c.k_key) for x in cases}
            assert c.k_range[0] in ks, (name, "k == lo missing")
            if c.k_range[0] != 1 and 1 >= c.k_range[0]:
                assert 1 in ks, (name, "k == 1 missing")
        if c.rows_key:
            rows = {x.get(c.rows_key) for x in cases}
            base_rows = c.base[c.rows_key]
            assert base_rows + 13 in rows, (name, "non-divisible rows "
                                                  "missing")
            # k == rows (the whole-row edge)
            assert any(x.get(c.k_key) == x.get(c.rows_key)
                       for x in cases), (name, "k == rows missing")
            # sublane boundary ±1 for the primary dtype
            s = contracts.dtype_sublane(c.dtypes[0])
            assert {s - 1, s, s + 1} & rows, (name, "sublane boundary "
                                                    "missing")
        if c.batch_key:
            assert any(x.get(c.batch_key) == 1 for x in cases), \
                (name, "single-row batch missing")


def test_static_engine_resolves_contracted_sites():
    """The cross-check's other half: the graft-kern static engine must
    fully resolve (exact VMEM accounting, computed blocks) every
    pallas_call in a contracted module — if it ever degrades to the
    literal fallback there, the computed audit has silently gone dark."""
    import os

    from raft_tpu.analysis.kernels import FileKernelVerifier

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("raft_tpu/ops/fused_topk.py", "raft_tpu/ops/ivf_scan.py",
                "raft_tpu/ops/beam_step.py",
                "raft_tpu/ops/graph_join.py"):
        path = os.path.join(repo, rel)
        with open(path) as f:
            v = FileKernelVerifier(path, f.read())
        v.run()
        assert v.report["sites"] >= 1, rel
        assert v.report["resolved"] == v.report["sites"], (rel, v.report)


def test_case_seeds_are_deterministic():
    """Failures must reproduce standalone: the per-case rng is seeded
    from the case content — STABLY ACROSS PROCESSES (a salted hash()
    would regenerate different data per rerun), pinned by the literal
    first draw below."""
    from raft_tpu.analysis.contract_drivers import _rng

    case = {"m": 4, "n": 16, "k": 2, "dtype": "float32"}
    a = _rng(dict(case)).standard_normal(8)
    b = _rng(dict(case)).standard_normal(8)
    np.testing.assert_array_equal(a, b)
    # cross-process stability: the seed is crc32-derived, so this first
    # draw is a constant of the case content, not of the interpreter
    np.testing.assert_allclose(a[0], _rng(case).standard_normal(1)[0])
    assert abs(float(a[0]) - 1.3822953003467113) < 1e-12, float(a[0])


def test_rabitq_estimator_unbiased():
    """ISSUE 11: the RaBitQ estimator is statistically unbiased and
    CALIBRATED against the exact-distance oracle — <q, r̂> regressed on
    <q, r> has slope ~1 and negligible intercept, and the estimated L2
    distances carry no systematic bias beyond their (theory-sized)
    noise. The UNCORRECTED sign estimator (fac replaced by the naive
    ||r||/sqrt(D) magnitude) fails the slope test — pinning that the
    fac = ||r||^2/||r||_1 correction is what buys unbiasedness."""
    import jax.numpy as jnp

    from raft_tpu.neighbors.ivf_pq import (
        _quant_pack_rabitq,
        unpack_sign_bits,
    )

    rng = np.random.default_rng(0xAB17)
    D, n, m = 128, 4000, 8
    r = rng.standard_normal((n, D)).astype(np.float32)   # residual rows
    q = rng.standard_normal((m, D)).astype(np.float32)   # query residuals
    packed, fac, n2 = _quant_pack_rabitq(jnp.asarray(r))
    signs = np.asarray(unpack_sign_bits(packed, D))
    fac = np.asarray(fac)
    n2 = np.asarray(n2)
    S = q @ signs.T                                       # [m, n]
    est = S * fac[None, :]
    true = q @ r.T
    err = est - true
    # per-pair error is mean-zero at the population scale: the residual
    # bias is a tiny fraction of the error spread (5-sigma bound on the
    # mean of n*m iid-ish samples)
    assert abs(err.mean()) < 5 * err.std() / np.sqrt(err.size)
    # calibration: least-squares slope of est on true ~ 1
    slope = (est * true).sum() / (true * true).sum()
    assert abs(slope - 1.0) < 0.02, slope
    # theory: err std ~ c * ||r|| * ||q|| / sqrt(D) with c ~ 0.6-0.9
    rel = err.std() / (np.linalg.norm(r, axis=1).mean()
                       * np.linalg.norm(q, axis=1).mean() / np.sqrt(D))
    assert 0.4 < rel < 1.2, rel
    # distance estimator: d^2 = ||q||^2 + ||r||^2 - 2 est vs exact
    qn = (q * q).sum(1)
    dest = qn[:, None] + n2[None, :] - 2 * est
    dtrue = ((q[:, None, :] - r[None, :, :]) ** 2).sum(-1)
    derr = dest - dtrue
    assert abs(derr.mean()) < 5 * derr.std() / np.sqrt(derr.size)
    # the naive magnitude scale is NOT calibrated (slope well below 1)
    naive = S * (np.linalg.norm(r, axis=1) / np.sqrt(D))[None, :]
    nslope = (naive * true).sum() / (true * true).sum()
    assert nslope < 0.9, nslope
