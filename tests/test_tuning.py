"""Tuning subsystem: dispatch-table round-trip, mode knob, consumer
wiring, and the per-shard CAGRA inline-eligibility budget.

The reference's select_k backend choice is a decision tree learned from
measurements (matrix/detail/select_k-inl.cuh:51-79); these tests pin the
TPU analog's machinery — measure -> persist -> load -> choose returns
the measured winner, analytic fallback on a miss — without depending on
which arm actually wins on this host's hardware.
"""

import json

import numpy as np
import pytest

from raft_tpu import tuning
from raft_tpu.tuning.table import DispatchTable


@pytest.fixture(autouse=True)
def _isolated_tuning(monkeypatch, tmp_path):
    """Every test starts with no mode override and no table resolved
    (packaged tables and RAFT_TPU_TUNING* env must not leak in)."""
    monkeypatch.delenv("RAFT_TPU_TUNING", raising=False)
    monkeypatch.delenv("RAFT_TPU_TUNING_TABLE", raising=False)
    monkeypatch.setattr(tuning, "_mode_override", None)
    missing = str(tmp_path / "missing.json")
    monkeypatch.setattr(tuning, "_table_path_override", missing)
    tuning.reload()
    yield
    tuning.reload()


def _write_table(path, op, entries, budgets=None):
    t = DispatchTable()
    for key, times in entries:
        t.record(op, key, times)
    for name, val in (budgets or {}).items():
        t.set_budget(name, val)
    t.save(str(path))
    return t


# ---------------------------------------------------------------------------
# table round-trip
# ---------------------------------------------------------------------------


def test_measure_persist_load_choose_round_trip(tmp_path):
    """The full loop: measure the real implementations, persist the
    winner, reload from JSON, and have tuning.choose return exactly the
    measured winner at that key."""
    from raft_tpu.tuning import microbench

    key = {"n": 2048, "k": 300, "batch": 4, "dtype": "float32"}
    times = microbench.bench_select(key, reps=2)
    # all three rungs compete at this shape (n >= 4K tiles)
    assert set(times) == {"top_k", "tournament", "hierarchical"}
    assert all(t > 0 for t in times.values())

    t = DispatchTable()
    winner = t.record("select_k", key, times)
    assert winner == min(times, key=times.get)
    path = tmp_path / "host.json"
    t.save(str(path))

    loaded = DispatchTable.load(str(path))
    assert loaded.lookup("select_k", key) == winner

    tuning.set_table_path(str(path))
    got = tuning.choose("select_k", key,
                        ["top_k", "tournament", "hierarchical"],
                        "analytic-fallback")
    assert got == winner


def test_choose_falls_back_on_missing_entry(tmp_path):
    path = tmp_path / "t.json"
    _write_table(path, "select_k",
                 [({"n": 8192, "k": 512, "batch": 16, "dtype": "float32"},
                   {"top_k": 5.0, "tournament": 1.0})])
    tuning.set_table_path(str(path))
    # nearby key interpolates to the measured winner
    assert tuning.choose(
        "select_k", {"n": 10000, "k": 600, "batch": 16, "dtype": "float32"},
        ["top_k", "tournament"], "top_k") == "tournament"
    # far-away key (outside the log2 trust radius) -> analytic fallback
    assert tuning.choose(
        "select_k", {"n": 128, "k": 2, "batch": 1, "dtype": "float32"},
        ["top_k", "tournament"], "FALLBACK") == "FALLBACK"
    # unknown op -> fallback
    assert tuning.choose(
        "nonesuch", {"n": 8192}, ["a", "b"], "FALLBACK") == "FALLBACK"
    # categorical mismatch (dtype) -> fallback
    assert tuning.choose(
        "select_k", {"n": 8192, "k": 512, "batch": 16, "dtype": "int32"},
        ["top_k"], "FALLBACK") == "FALLBACK"


def test_choose_ignores_winner_outside_candidates(tmp_path):
    """A table winner the call site can't use (dtype/layout constraint)
    must never be returned — the entry is skipped, not clamped."""
    path = tmp_path / "t.json"
    _write_table(path, "select_k",
                 [({"n": 8192, "k": 512, "batch": 16},
                   {"top_k": 5.0, "tournament": 1.0})])
    tuning.set_table_path(str(path))
    assert tuning.choose("select_k", {"n": 8192, "k": 512, "batch": 16},
                         ["top_k"], "top_k") == "top_k"


def test_mode_off_freezes_to_analytic(tmp_path):
    path = tmp_path / "t.json"
    _write_table(path, "select_k",
                 [({"n": 8192, "k": 512, "batch": 16, "dtype": "float32"},
                   {"top_k": 5.0, "tournament": 1.0})])
    tuning.set_table_path(str(path))
    tuning.set_mode("off")
    assert tuning.choose(
        "select_k", {"n": 8192, "k": 512, "batch": 16, "dtype": "float32"},
        ["top_k", "tournament"], "ANALYTIC") == "ANALYTIC"
    assert tuning.budget("cagra_inline_bytes", 123) == 123


def test_env_knob_and_bad_table(monkeypatch, tmp_path):
    monkeypatch.setenv("RAFT_TPU_TUNING", "off")
    assert tuning.mode() == "off"
    monkeypatch.setenv("RAFT_TPU_TUNING", "bogus")
    assert tuning.mode() == "table"
    # unreadable table == no table: choose degrades to fallback
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    tuning.set_table_path(str(bad))
    assert tuning.choose("select_k", {"n": 8192}, ["top_k"],
                         "FB") == "FB"


def test_budget_lookup(tmp_path):
    path = tmp_path / "t.json"
    _write_table(path, "select_k", [], budgets={"cagra_inline_bytes": 999})
    tuning.set_table_path(str(path))
    assert tuning.budget("cagra_inline_bytes", 5) == 999
    assert tuning.budget("unknown_budget", 5) == 5


def test_table_version_gate(tmp_path):
    p = tmp_path / "v0.json"
    p.write_text(json.dumps({"version": 0, "ops": {}}))
    with pytest.raises(ValueError, match="version"):
        DispatchTable.load(str(p))


# ---------------------------------------------------------------------------
# consumer wiring
# ---------------------------------------------------------------------------


def test_select_k_consults_table(tmp_path):
    """A table entry overrides the analytic projection at a real
    select_k call — and the tournament answer stays exact."""
    import jax.numpy as jnp

    from raft_tpu.matrix.select_k import select_k

    path = tmp_path / "t.json"
    # force the tournament where the analytic rule says top_k (k=64)
    _write_table(path, "select_k",
                 [({"n": 4096, "k": 64, "batch": 4, "dtype": "float32"},
                   {"top_k": 9.0, "tournament": 1.0})])
    tuning.set_table_path(str(path))
    from raft_tpu.matrix.select_k import dispatch_select_impl

    assert dispatch_select_impl(4, 4096, 64, jnp.float32) == "tournament"
    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 4096)).astype(np.float32)
    v, i = select_k(jnp.asarray(x), 64)
    np.testing.assert_allclose(np.asarray(v), np.sort(x, axis=1)[:, :64])
    # integers can never land on the float-only tournament
    assert dispatch_select_impl(4, 4096, 64, jnp.int32) == "top_k"


def test_merge_topk_consults_its_own_op(tmp_path, monkeypatch):
    """merge_topk looks up the dedicated 'merge_topk' op key; a winner
    there routes the exact merge arm."""
    import importlib

    import jax.numpy as jnp

    sk = importlib.import_module("raft_tpu.matrix.select_k")
    from raft_tpu.neighbors.common import merge_topk

    path = tmp_path / "t.json"
    _write_table(path, "merge_topk",
                 [({"n": 2048, "k": 32, "batch": 8, "dtype": "float32"},
                   {"top_k": 9.0, "tournament": 1.0})])
    tuning.set_table_path(str(path))
    calls = []
    orig = sk._tournament_topk
    monkeypatch.setattr(sk, "_tournament_topk",
                        lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1])
    rng = np.random.default_rng(4)
    d = rng.standard_normal((8, 2048)).astype(np.float32)
    ids = np.broadcast_to(np.arange(2048, dtype=np.int32), (8, 2048))
    v, i = merge_topk(jnp.asarray(d), jnp.asarray(ids), 32)
    assert calls, "merge_topk ignored its table entry"
    np.testing.assert_allclose(np.asarray(v), np.sort(d, axis=1)[:, :32],
                               rtol=1e-6)


def test_resolve_scan_impl_consults_table(tmp_path):
    """ivf_flat/_pq scan-impl resolution honors a measured winner within
    the eligible set (xla-only on CPU: a 'pallas' entry can't leak in)."""
    from raft_tpu.neighbors.ivf_flat import _resolve_scan_impl

    path = tmp_path / "t.json"
    _write_table(path, "ivf_scan",
                 [({"cap": 512, "k": 10, "approx": True},
                   {"pallas": 1.0, "xla": 9.0})])
    tuning.set_table_path(str(path))
    # CPU: pallas not a candidate regardless of the table
    assert _resolve_scan_impl("auto", 512, 10, approx=True) == "xla"
    # explicit request always wins
    assert _resolve_scan_impl("xla", 512, 10) == "xla"


def test_resolve_bf_impl_consults_table(tmp_path, monkeypatch):
    """brute_force backend resolution (op fused_topk_tile): a measured
    fused winner is honored only where the fused kernel is a candidate
    (TPU, unfiltered, expanded metric) — on CPU the scan arm answers no
    matter what the table says; on a (faked) TPU backend the winner's
    variant:tile string comes straight through."""
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors.brute_force import _resolve_bf_impl

    path = tmp_path / "t.json"
    _write_table(path, "fused_topk_tile",
                 [({"m": 512, "n": 20000, "d": 64, "k": 10},
                   {"scan": 9.0, "fused_exact:1024": 1.0})])
    tuning.set_table_path(str(path))
    args = (512, 20000, 64, 10, DistanceType.L2Expanded)
    # CPU: fused never a candidate
    assert _resolve_bf_impl("auto", *args, filtered=False,
                            approx_ok=False) == "scan"
    # TPU: measured fused winner adopted, tile included
    monkeypatch.setattr(tuning, "backend_name", lambda: "tpu")
    assert _resolve_bf_impl("auto", *args, filtered=False,
                            approx_ok=False) == "fused_exact:1024"
    # filtered searches stay on the scan path (kernel has no filter)
    assert _resolve_bf_impl("auto", *args, filtered=True,
                            approx_ok=False) == "scan"
    # explicit request always wins
    assert _resolve_bf_impl("scan", *args, filtered=False,
                            approx_ok=True) == "scan"


def test_bench_fused_topk_scan_arm_runs_on_cpu():
    """The fused_topk_tile microbench's scan arm runs end to end on CPU
    (the arm the committed cpu.json captures); fused candidates are
    interpret-gated and excluded here for time."""
    from raft_tpu.tuning.microbench import bench_fused_topk

    times = bench_fused_topk({"m": 16, "n": 512, "d": 16, "k": 5},
                             ["scan"], reps=1)
    assert set(times) == {"scan"} and times["scan"] > 0


def test_pq_cache_kind_auto_consults_table(tmp_path):
    """cache_dtype='auto' stays fidelity-first (i8 whenever it fits —
    the table must NOT flip a recall-affecting rung), and consults the
    measured pq_scan race only between the recall-tied half-byte rungs
    (i4 vs pq4) once i8 is over budget."""
    from raft_tpu.neighbors.ivf_pq import _cache_kind_for

    # i8 infeasible, i4 + pq4 feasible: C*cap*rot = 16G > 10G budget,
    # half-byte footprint 8G fits
    C, cap, rot, pqd = 1024, 16384, 1024, 1024
    path = tmp_path / "t.json"
    _write_table(path, "pq_scan",
                 [({"n_lists": C, "cap": cap, "rot": rot, "pq_dim": pqd,
                    "pq_bits": 4},
                   {"i4": 9.0, "pq4": 1.0})])
    tuning.set_table_path(str(path))
    got = _cache_kind_for(True, "auto", C, cap, rot, pq_bits=4,
                          pq_dim=pqd, per_subspace=True)
    assert got == "pq4"
    # miss (mode off) -> analytic i4-first compressed rung
    tuning.set_mode("off")
    got = _cache_kind_for(True, "auto", C, cap, rot, pq_bits=4,
                          pq_dim=pqd, per_subspace=True)
    assert got == "i4"
    # i8 within budget: always i8, whatever the table says
    assert _cache_kind_for(True, "auto", 64, 512, 64, pq_bits=4,
                           pq_dim=32, per_subspace=True) == "i8"


# ---------------------------------------------------------------------------
# measure mode
# ---------------------------------------------------------------------------


def test_measure_mode_measures_and_caches(monkeypatch):
    """RAFT_TPU_TUNING=measure: an uncovered select_k key is measured
    once (result cached in-process) and the measured winner returned."""
    from raft_tpu.tuning import microbench

    tuning.set_mode("measure")
    calls = []
    real = microbench.bench_select

    def spy(key, candidates=None, reps=3):
        calls.append(key)
        return real(key, candidates, reps=1)

    monkeypatch.setattr(microbench, "measure_op",
                        lambda op, key, cands: spy(key, cands))
    key = {"n": 1024, "k": 16, "batch": 2, "dtype": "float32"}
    w1 = tuning.choose("select_k", key, ["top_k", "tournament"], "top_k")
    w2 = tuning.choose("select_k", key, ["top_k", "tournament"], "top_k")
    assert w1 == w2
    assert w1 in ("top_k", "tournament")
    assert len(calls) == 1, "second call must hit the in-process cache"


# ---------------------------------------------------------------------------
# per-shard CAGRA inline eligibility (ADVICE r5 finding 3)
# ---------------------------------------------------------------------------


def test_cagra_inline_eligible_budgets_per_shard(monkeypatch):
    """The inline gate budgets rows*row_bytes (per-shard search-time
    residency), not total n*row_bytes: an 8-way sharded dataset 4x over
    the single-device budget stays eligible because each shard holds
    only 1/8 of the table."""
    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.beam_step import packed_row_layout

    d, deg = 64, 32
    row_bytes = 4 * packed_row_layout(deg, d, False)[3]
    budget = cagra._INLINE_BUDGET
    # single-device: n over budget -> ineligible (unchanged behavior)
    n_big = budget // row_bytes * 4
    assert not cagra._inline_eligible(n_big, d, deg, True)
    # same dataset 8-way sharded: per-shard residency is n/8 * row_bytes
    # = budget/2 -> eligible
    assert cagra._inline_eligible(n_big, d, deg, True,
                                  max_rows=n_big // 8)
    # per-shard rows alone over budget -> still ineligible
    assert not cagra._inline_eligible(n_big, d, deg, True,
                                      max_rows=n_big)
    # misaligned dim never packs
    assert not cagra._inline_eligible(1000, 63, deg, True)


def test_cagra_inline_budget_tunable(tmp_path):
    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.beam_step import packed_row_layout

    d, deg = 64, 32
    row_bytes = 4 * packed_row_layout(deg, d, False)[3]
    n = 4096
    path = tmp_path / "t.json"
    # a table budget below this dataset's residency disables inlining
    _write_table(path, "select_k", [],
                 budgets={"cagra_inline_bytes": n * row_bytes // 2})
    tuning.set_table_path(str(path))
    assert not cagra._inline_eligible(n, d, deg, True)
    tuning.set_mode("off")        # off-mode restores the analytic budget
    assert cagra._inline_eligible(n, d, deg, True)


# ---------------------------------------------------------------------------
# capture pipeline (tiny grid)
# ---------------------------------------------------------------------------


def test_capture_emits_valid_loadable_table(tmp_path, monkeypatch):
    """capture() on a stubbed-down grid emits a table that loads and
    serves winners — the committed-artifact pipeline end to end."""
    from raft_tpu.tuning import microbench

    monkeypatch.setattr(
        microbench, "select_grid",
        lambda quick=True: [{"n": 1024, "k": 16, "batch": 2,
                             "dtype": "float32"}])
    monkeypatch.setattr(
        microbench, "merge_grid",
        lambda quick=True: [{"n": 512, "k": 8, "batch": 4,
                             "dtype": "float32"}])
    t = microbench.capture(backend="testhost", quick=True, reps=1,
                           ops=["select_k", "merge_topk"], verbose=False)
    assert t.n_entries("select_k") == 1
    assert t.n_entries("merge_topk") == 1
    assert t.budget("cagra_inline_bytes") is not None
    path = tmp_path / "testhost.json"
    t.save(str(path))
    tuning.set_table_path(str(path))
    w = tuning.choose("select_k",
                      {"n": 1024, "k": 16, "batch": 2, "dtype": "float32"},
                      ["top_k", "tournament", "hierarchical"], "FB")
    assert w in ("top_k", "tournament", "hierarchical")


def test_fused_topk_candidate_enumeration_is_shared():
    """ISSUE 10 satellite: ONE home for the fused-tile candidate set —
    brute_force dispatches over exactly these strings and graft-kern
    audits exactly these values."""
    impls = tuning.fused_topk_candidate_impls(10, approx_ok=True)
    assert impls == [f"fused_exact:{t}" for t in tuning.FUSED_TOPK_TILES] \
        + [f"fused_fold:{t}" for t in tuning.FUSED_TOPK_TILES]
    # variant extraction budgets: exact caps at 128, fold at 256
    assert all(s.startswith("fused_fold") for s in
               tuning.fused_topk_candidate_impls(200, approx_ok=True))
    assert tuning.fused_topk_candidate_impls(300, approx_ok=True) == []
    assert all(s.startswith("fused_exact") for s in
               tuning.fused_topk_candidate_impls(64, approx_ok=False))


def test_kernel_shape_candidates_cover_winner_domain(tmp_path):
    """The verifier's audited tile domain = the canonical race set, the
    analytic halving floor, and any extra tile a site-captured table's
    winner strings carry."""
    doms = tuning.kernel_shape_candidates()
    for t in tuning.FUSED_TOPK_TILES:
        assert t in doms["tile_n"]
    assert tuning.FUSED_TOPK_TILE_FLOOR in doms["tile_n"]
    assert doms["variant"] == ("exact", "fold")
    # a site-captured table with a custom tile widens the domain
    t = DispatchTable({"version": 1, "backend": "x", "ops": {
        "fused_topk_tile": {"entries": [
            {"key": {"m": 1, "n": 2, "d": 3, "k": 4},
             "times_ms": {"fused_exact:768": 1.0},
             "winner": "fused_exact:768"}]}}, })
    path = tmp_path / "x.json"
    t.save(str(path))
    tuning.set_table_path(str(path))
    try:
        assert 768 in tuning.kernel_shape_candidates()["tile_n"]
    finally:
        tuning.set_table_path(None)


def test_rabitq_matched_refine_ratio_filter():
    """The pq_scan race's loss-aware eligibility (ISSUE 11): the rabitq
    arm races at the smallest refine_ratio that clears the recall
    target, and is filtered out entirely — BEFORE any timing — when no
    ratio does (the binned_loss_fits pattern: a table winner is never
    recall-re-filtered at dispatch)."""
    from raft_tpu.tuning.microbench import rabitq_matched_refine_ratio

    assert rabitq_matched_refine_ratio({2: 0.9, 4: 0.95}, 0.88) == 2
    assert rabitq_matched_refine_ratio({2: 0.80, 4: 0.92}, 0.88) == 4
    assert rabitq_matched_refine_ratio({2: 0.5, 4: 0.6}, 0.88) is None
    assert rabitq_matched_refine_ratio({}, 0.88) is None


def test_pq_scan_auto_ladder_rabitq_gating():
    """cache-kind resolution: rabitq is reachable explicitly and as a
    MEASURED table winner, but the analytic auto fallback never picks
    it — when nothing fits the budget, auto still returns None so
    plain search keeps its exact PQ code scan (a silent 1-bit
    downgrade would regress plain-search recall; review fix, r10)."""
    from raft_tpu.neighbors.ivf_pq import _CACHE_BUDGET, _cache_kind_for
    from raft_tpu import tuning

    # explicit request, always feasible at small scale (any rot —
    # partial last word is padded)
    assert _cache_kind_for(True, "rabitq", 4, 128, 48) == "rabitq"
    # shapes where i8/i4/pq4 all blow the budget but the 1-bit cache
    # fits: the auto FALLBACK must stay None (tuning off = pure
    # analytic answer)
    C = 1024
    cap = 8192
    rot = (_CACHE_BUDGET // (C * cap) + 8) // 8 * 8 + 256
    tuning.set_mode("off")
    try:
        assert _cache_kind_for(True, "auto", C, cap, rot + 4) is None
    finally:
        tuning.set_mode(None)
