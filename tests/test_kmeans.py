"""Cluster-layer tests (reference test strategy: cpp/test/cluster/*,
pylibraft test_kmeans.py — oracle = sklearn-style checks on blob data)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster import (
    KMeansBalancedParams,
    KMeansParams,
    cluster_cost,
    compute_new_centroids,
    init_plus_plus,
    kmeans,
    kmeans_balanced,
)
from raft_tpu.random.generators import make_blobs


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(42)
    centers = rng.uniform(-10.0, 10.0, size=(8, 16)).astype(np.float32)
    x, labels = make_blobs(
        n_samples=2000, n_features=16, centers=centers, cluster_std=0.3, seed=42
    )
    return np.asarray(x), np.asarray(labels), centers


def test_kmeans_fit_recovers_blobs(blobs):
    x, true_labels, true_centers = blobs
    params = KMeansParams(n_clusters=8, max_iter=50, seed=0)
    centers, inertia, n_iter = kmeans.fit(params, x)
    assert centers.shape == (8, 16)
    assert int(n_iter) >= 1
    # every true center should have a fitted center very close to it
    d = np.linalg.norm(
        np.asarray(centers)[None, :, :] - true_centers[:, None, :], axis=-1
    )
    assert d.min(axis=1).max() < 0.5

    labels = np.asarray(kmeans.predict(params, centers, x))
    # cluster assignment must agree with ground truth up to permutation:
    # points sharing a true label share a predicted label
    for t in range(8):
        vals, counts = np.unique(labels[true_labels == t], return_counts=True)
        assert counts.max() / counts.sum() > 0.95


def test_kmeans_inertia_decreases(blobs):
    x, _, _ = blobs
    params = KMeansParams(n_clusters=8, max_iter=1, seed=1, init="random")
    _, inertia1, _ = kmeans.fit(params, x)
    params = KMeansParams(n_clusters=8, max_iter=30, seed=1, init="random")
    _, inertia30, n_iter = kmeans.fit(params, x)
    # random init on 8 blobs must take multiple Lloyd iterations — guards
    # against the convergence test tripping on the first iteration
    assert int(n_iter) > 1
    assert float(inertia30) < float(inertia1) * 0.99


def test_cluster_cost_matches_oracle(blobs):
    x, _, _ = blobs
    centers = x[:8]
    d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    expected = d2.min(axis=1).sum()
    got = float(cluster_cost(x, centers))
    np.testing.assert_allclose(got, expected, rtol=1e-4)


def test_compute_new_centroids_oracle(blobs):
    x, _, _ = blobs
    centers = x[:8].astype(np.float32)
    d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    labels = d2.argmin(axis=1)
    expected = np.stack(
        [x[labels == c].mean(axis=0) if (labels == c).any() else centers[c]
         for c in range(8)]
    )
    got = np.asarray(compute_new_centroids(x, centers))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


def test_init_plus_plus_spreads_centers(blobs):
    x, _, true_centers = blobs
    centers = np.asarray(init_plus_plus(x, 8, seed=3))
    assert centers.shape == (8, 16)
    # k-means++ on tight blobs should hit most distinct blobs; a sampled
    # point sits ~cluster_std*sqrt(d) ~= 1.2 from its blob center
    d = np.linalg.norm(centers[None, :, :] - true_centers[:, None, :], axis=-1)
    hit = (d.min(axis=1) < 3.0).sum()
    assert hit >= 6


def test_kmeans_weighted(blobs):
    x, _, _ = blobs
    w = np.ones(x.shape[0], np.float32)
    params = KMeansParams(n_clusters=8, max_iter=20, seed=0)
    c1, i1, _ = kmeans.fit(params, x)
    c2, i2, _ = kmeans.fit(params, x, sample_weights=w)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), rtol=1e-4, atol=1e-4)


def test_balanced_fit_balances_sizes(blobs):
    x, _, _ = blobs
    params = KMeansBalancedParams(n_clusters=16, n_iters=20, seed=0)
    centers = kmeans_balanced.fit(params, x)
    assert centers.shape == (16, 16)
    labels = np.asarray(kmeans_balanced.predict(params, centers, x))
    sizes = np.bincount(labels, minlength=16)
    assert sizes.min() > 0  # no starved clusters
    # balanced trainer: no cluster hogs the data
    assert sizes.max() < x.shape[0] * 0.5


def test_balanced_hierarchical_path():
    # n_clusters big enough to trigger the meso/fine hierarchy
    rng = np.random.default_rng(0)
    x = rng.standard_normal((5000, 8)).astype(np.float32)
    params = KMeansBalancedParams(n_clusters=64, n_iters=10, seed=0)
    centers, labels = kmeans_balanced.fit_predict(params, x)
    assert centers.shape == (64, 8)
    sizes = np.bincount(np.asarray(labels), minlength=64)
    assert (sizes > 0).sum() >= 60  # nearly all clusters populated
    assert sizes.max() < 0.1 * x.shape[0]


def test_balanced_predict_inner_product():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((256, 8)).astype(np.float32)
    centers = rng.standard_normal((4, 8)).astype(np.float32)
    from raft_tpu.distance.types import DistanceType

    # exact-match contract needs the f32 compute path (default is bf16,
    # which may flip near-tied argmaxes)
    params = KMeansBalancedParams(
        n_clusters=4, metric=DistanceType.InnerProduct, compute_dtype="f32"
    )
    labels = np.asarray(kmeans_balanced.predict(params, centers, x))
    expected = (x @ centers.T).argmax(axis=1)
    np.testing.assert_array_equal(labels, expected)


def test_calc_centers_and_sizes():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((100, 4)).astype(np.float32)
    labels = rng.integers(0, 5, 100).astype(np.int32)
    centers, sizes = kmeans_balanced.calc_centers_and_sizes(x, labels, 5)
    np.testing.assert_array_equal(np.asarray(sizes), np.bincount(labels, minlength=5))
    for c in range(5):
        if (labels == c).any():
            np.testing.assert_allclose(
                np.asarray(centers)[c], x[labels == c].mean(0), rtol=1e-4, atol=1e-4
            )


def test_kmeans_cosine_metric():
    # regression: KMeansParams.metric must be honored by fit/predict
    from raft_tpu.distance.types import DistanceType

    rng = np.random.default_rng(5)
    # two directional clusters on the unit sphere with different magnitudes
    a = rng.standard_normal((200, 8)) * 0.1 + np.eye(8)[0] * 1.0
    b = rng.standard_normal((200, 8)) * 0.1 + np.eye(8)[1] * 5.0
    x = np.concatenate([a, b]).astype(np.float32)
    params = KMeansParams(
        n_clusters=2, max_iter=30, seed=0, metric=DistanceType.CosineExpanded
    )
    centers, inertia, _ = kmeans.fit(params, x)
    labels = np.asarray(kmeans.predict(params, centers, x))
    assert len(np.unique(labels[:200])) == 1
    assert len(np.unique(labels[200:])) == 1
    assert labels[0] != labels[200]


def test_find_k_recovers_cluster_count(blobs):
    """CH-objective bisection lands on (or next to) the true k=8 for
    well-separated blobs (reference kmeans_auto_find_k.cuh semantics)."""
    x, _, _ = blobs
    k, inertia, _ = kmeans.find_k(x, kmax=16, kmin=2, max_iter=30, seed=0)
    assert 7 <= k <= 9
    assert float(inertia) > 0


def test_kmeans_rejects_unsupported_metric():
    from raft_tpu.distance.types import DistanceType

    x = np.zeros((10, 3), np.float32)
    with pytest.raises(ValueError):
        kmeans.fit(KMeansParams(n_clusters=2, metric=DistanceType.InnerProduct), x)


def test_balanced_fit_inner_product_metric():
    # regression: metric must reach the balancing EM (was silently L2)
    from raft_tpu.distance.types import DistanceType

    rng = np.random.default_rng(9)
    x = rng.standard_normal((600, 8)).astype(np.float32)
    params = KMeansBalancedParams(
        n_clusters=8, n_iters=10, metric=DistanceType.InnerProduct, seed=0)
    centers = kmeans_balanced.fit(params, x)
    labels = np.asarray(kmeans_balanced.predict(params, centers, x))
    # assignment must be by max inner product
    expected = (x @ np.asarray(centers).T).argmax(1)
    np.testing.assert_array_equal(labels, expected)
