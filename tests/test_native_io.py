"""Native IO runtime tests — C++ prefetcher vs numpy oracle, and a
file-streamed IVF-PQ build end to end."""

import numpy as np
import pytest

from raft_tpu import native
from raft_tpu.bench.datasets import write_bin
from raft_tpu.utils.batch import FileBatchLoadIterator


def test_native_builds_and_reads(tmp_path):
    p = str(tmp_path / "blob.bin")
    data = np.arange(4096, dtype=np.uint8)
    with open(p, "wb") as fp:
        fp.write(data.tobytes())
    out = native.read_block(p, 100, 1000)
    np.testing.assert_array_equal(out, data[100:1100])
    # short read at the tail
    out = native.read_block(p, 4000, 1000)
    np.testing.assert_array_equal(out, data[4000:])


def test_prefetcher_stream(tmp_path):
    p = str(tmp_path / "stream.bin")
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
    with open(p, "wb") as fp:
        fp.write(data.tobytes())
    got = []
    for blk in native.FilePrefetcher(p, offset=16, block_bytes=70_000,
                                     total_bytes=900_000, depth=3):
        got.append(blk)
    cat = np.concatenate(got)
    np.testing.assert_array_equal(cat, data[16 : 16 + 900_000])


def test_file_batch_iterator(tmp_path):
    p = str(tmp_path / "rows.fbin")
    rng = np.random.default_rng(1)
    arr = rng.standard_normal((1000, 24)).astype(np.float32)
    write_bin(p, arr)
    it = FileBatchLoadIterator(p, batch_rows=256, pad_to_full=True)
    assert it.shape == (1000, 24)
    assert len(it) == 4
    seen = np.zeros((1024, 24), np.float32)
    for off, batch in it:
        seen[off : off + 256] = np.asarray(batch)
    np.testing.assert_allclose(seen[:1000], arr, rtol=1e-6)
    np.testing.assert_array_equal(seen[1000:], 0)


def test_streaming_pq_build_from_file(tmp_path):
    from raft_tpu.neighbors import ivf_pq

    p = str(tmp_path / "ds.fbin")
    rng = np.random.default_rng(2)
    arr = rng.standard_normal((4000, 32)).astype(np.float32)
    write_bin(p, arr)
    # file-streamed encode: read via the iterator, build batch by batch
    it = FileBatchLoadIterator(p, batch_rows=1024, pad_to_full=False)
    chunks = [np.asarray(b) for _, b in it]
    full = np.concatenate(chunks)
    np.testing.assert_allclose(full, arr, rtol=1e-6)
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, pq_dim=8), full, batch_size=1024
    )
    assert index.size == 4000


def test_refine_host_matches_device(tmp_path):
    """Threaded host refine == device refine (the reference's OpenMP
    refine_host parity, detail/refine_host-inl.hpp)."""
    import numpy as np
    from raft_tpu.neighbors import refine, refine_host

    rng = np.random.default_rng(4)
    n, d, m, c, k = 3000, 48, 128, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    cand = rng.integers(0, n, (m, c)).astype(np.int32)
    cand[:, 5] = -1                      # invalid slots
    hd, hi = refine_host(x, q, cand, k)
    dd, di = refine(x, q, cand, k)
    np.testing.assert_array_equal(hi, np.asarray(di))
    np.testing.assert_allclose(hd, np.asarray(dd), rtol=1e-4, atol=1e-4)
    # memmap-backed dataset (the host variant's reason to exist)
    path = tmp_path / "base.npy"
    np.save(path, x)
    mm = np.load(path, mmap_mode="r")
    md, mi = refine_host(mm, q, cand, k)
    np.testing.assert_array_equal(mi, hi)


def test_search_file_streaming(tmp_path):
    """File-backed query set larger than one batch streams through the
    regular search and matches the in-memory result."""
    import numpy as np
    from raft_tpu.bench.datasets import write_bin
    from raft_tpu.neighbors import brute_force
    from raft_tpu.neighbors.stream import search_file, search_host_array

    rng = np.random.default_rng(5)
    n, d, m, k = 4000, 32, 700, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    path = str(tmp_path / "queries.fbin")
    write_bin(path, q)
    index = brute_force.build(x, "sqeuclidean")

    class _Mod:
        @staticmethod
        def search(sp, index, batch, k):
            return brute_force.search(index, batch, k)

    sd, si = search_file(_Mod, None, index, path, k, batch_rows=256)
    dd, di = brute_force.search(index, q, k)
    np.testing.assert_array_equal(si, np.asarray(di))
    hd2, hi2 = search_host_array(_Mod, None, index, q, k, batch_rows=256)
    np.testing.assert_array_equal(hi2, np.asarray(di))
