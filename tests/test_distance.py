"""pairwise_distance vs numpy/scipy oracles — the analog of the reference's
per-metric distance tests (cpp/test/distance/dist_*.cu)."""

import numpy as np
import pytest

from raft_tpu.distance import DistanceType, pairwise_distance, is_min_close
from tests.oracles import naive_pairwise

GENERAL_METRICS = [
    "sqeuclidean", "euclidean", "l1", "chebyshev", "inner_product",
    "cosine", "correlation", "canberra", "minkowski", "braycurtis", "hamming",
]
POSITIVE_METRICS = ["jensenshannon", "hellinger", "kl_divergence"]
BOOLEAN_METRICS = ["russellrao", "jaccard", "dice"]


@pytest.mark.parametrize("metric", GENERAL_METRICS)
@pytest.mark.parametrize("m,n,d", [(33, 47, 17), (128, 256, 64)])
def test_general_metrics(rng, metric, m, n, d):
    x = rng.standard_normal((m, d)).astype(np.float32)
    y = rng.standard_normal((n, d)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric, metric_arg=3.0))
    want = naive_pairwise(x, y, metric, p=3.0)
    # expanded-form metrics accumulate in fp32 (MXU) vs the fp64 oracle —
    # same tolerance story as the reference's distance tests
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("metric", POSITIVE_METRICS)
def test_positive_metrics(rng, metric):
    m, n, d = 40, 50, 32
    x = rng.random((m, d)).astype(np.float32) + 0.01
    y = rng.random((n, d)).astype(np.float32) + 0.01
    if metric in ("jensenshannon", "hellinger", "kl_divergence"):
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric))
    want = naive_pairwise(x, y, metric)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("metric", BOOLEAN_METRICS)
def test_boolean_metrics(rng, metric):
    m, n, d = 30, 35, 64
    x = (rng.random((m, d)) < 0.3).astype(np.float32)
    y = (rng.random((n, d)) < 0.3).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric))
    want = naive_pairwise(x, y, metric)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_haversine(rng):
    x = np.stack([
        rng.uniform(-np.pi / 2, np.pi / 2, 20),
        rng.uniform(-np.pi, np.pi, 20),
    ], axis=1).astype(np.float32)
    y = np.stack([
        rng.uniform(-np.pi / 2, np.pi / 2, 25),
        rng.uniform(-np.pi, np.pi, 25),
    ], axis=1).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "haversine"))
    want = naive_pairwise(x, y, "haversine")
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_tiled_matches_untiled(rng):
    # elementwise path with forced small tiles must equal one-shot result
    x = rng.standard_normal((70, 19)).astype(np.float32)
    y = rng.standard_normal((90, 19)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "l1", tile_m=16, tile_n=32))
    want = naive_pairwise(x, y, "l1")
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_is_min_close():
    assert not is_min_close(DistanceType.InnerProduct)
    assert is_min_close(DistanceType.L2Expanded)


def test_l2_self_distance_zero(rng):
    x = rng.standard_normal((50, 33)).astype(np.float32)
    d = np.asarray(pairwise_distance(x, x, "sqeuclidean"))
    assert (np.diag(d) >= 0).all()
    assert np.diag(d).max() < 1e-2
