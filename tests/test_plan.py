"""graft-plan suite (ISSUE 20; docs/plans.md).

Four families, all riding tier-1 under the ``plan`` marker:

* IR validation negatives — the stage contracts the hand-wired
  pipelines enforced by construction (cyclic DAG, filter-after-merge,
  score_fuse arity, widening shortlists) now fail loudly at plan
  build time;
* serialization round-trip — every canonical plan survives
  ``to_json``/``from_json`` intact (plans ship to sharded workers as
  JSON, so the wire format is the contract);
* plan-vs-legacy bitwise matrix — the serve engine's compiled-plan
  dispatch returns byte-identical (distances AND ids) answers to the
  library entry points it replaced, across index types x tombstone x
  prefilter x tiered source, and across an upsert + compact hot-swap;
* end-to-end acceptance — the hybrid dense+sparse ``score_fuse`` plan
  against a fused numpy oracle, the sharded rabitq worker/router
  subplan split bitwise vs single-process ``search_refined``, and
  zero steady-state retraces over mixed-size post-warmup traffic
  (the GL007 ``_cache_size`` hook via ``serve.trace_cache_sizes``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import plan as plan_mod
from raft_tpu import serve
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.neighbors.common import BitsetFilter
from raft_tpu.plan import Node, Plan, PlanError

pytestmark = pytest.mark.plan


# ---------------------------------------------------------------------------
# IR validation
# ---------------------------------------------------------------------------


def _scan(nid="s", width="shortlist", **kw):
    return Node(id=nid, stage="scan", op="ivf_pq.search",
                params={"width": width}, **kw)


def test_canonical_plans_validate():
    for p in [
        plan_mod.refined_plan("tiered"),
        plan_mod.refined_plan("cache"),
        plan_mod.refined_plan("codes"),
        plan_mod.hybrid_plan(),
        plan_mod.sharded_ivf_pq_plan(8, 32, 32, tail="codes"),
        plan_mod.sharded_ivf_pq_plan(8, 32, 8, local_rerank=True),
        plan_mod.serve_plan("ivf_pq", "plain"),
        plan_mod.serve_plan("ivf_pq", "refined_tiered"),
        plan_mod.serve_plan("ivf_pq", "exact"),
        plan_mod.serve_plan("brute_force", "raw_refine"),
        plan_mod.serve_plan("hybrid", "plain"),
    ]:
        order = plan_mod.validate(p)
        assert [n.id for n in order]  # toposort returned every node
        assert len(order) == len(p.nodes)


def test_serialization_round_trip():
    for p in [
        plan_mod.refined_plan("tiered"),
        plan_mod.hybrid_plan(fuse_expand=8),
        plan_mod.sharded_ivf_pq_plan(10, 40, 40, tail="tiered"),
    ]:
        assert plan_mod.from_json(plan_mod.to_json(p)) == p
        d = plan_mod.to_dict(p)
        assert d["schema"] == 1
        assert plan_mod.from_dict(d) == p


def test_from_dict_rejects_unknown_schema():
    d = plan_mod.to_dict(plan_mod.refined_plan("codes"))
    d["schema"] = 99
    with pytest.raises(PlanError, match="schema"):
        plan_mod.from_dict(d)


def test_validate_rejects_cycle():
    p = Plan(name="cyc", nodes=(
        _scan("s"),
        Node(id="r1", stage="rerank", op="x", params={"width": "k"},
             inputs=("s", "r2")),
        Node(id="r2", stage="rerank", op="x", params={"width": "k"},
             inputs=("r1",)),
    ), output="r1")
    with pytest.raises(PlanError, match="cycle"):
        plan_mod.validate(p)


def test_validate_rejects_filter_after_merge():
    p = Plan(name="fam", nodes=(
        _scan("s"),
        Node(id="m", stage="merge", op="topk", params={"width": "k"},
             inputs=("s",)),
        Node(id="f", stage="filter", op="bitset", inputs=("m",)),
        Node(id="s2", stage="scan", op="x", params={"width": "k"},
             inputs=("f",)),
    ), output="s2")
    with pytest.raises(PlanError, match="cannot feed"):
        plan_mod.validate(p)


def test_validate_rejects_stage_contract_mismatches():
    # score_fuse with a single candidate leg
    p = Plan(name="one-leg", nodes=(
        _scan("s"),
        Node(id="f", stage="score_fuse", op="weighted",
             params={"width": "fuse"}, inputs=("s",)),
        Node(id="m", stage="merge", op="topk", params={"width": "k"},
             inputs=("f",)),
    ), output="m")
    with pytest.raises(PlanError, match="exactly 2 candidate legs"):
        plan_mod.validate(p)

    # rerank with nothing to rerank
    p = Plan(name="no-cand", nodes=(
        Node(id="f", stage="filter", op="bitset"),
        Node(id="r", stage="rerank", op="x", params={"width": "k"},
             inputs=("f",)),
    ), output="r")
    with pytest.raises(PlanError, match="no candidate input"):
        plan_mod.validate(p)

    # a rerank that WIDENS its shortlist reads rows the first stage
    # never scored
    p = Plan(name="widen", nodes=(
        Node(id="s", stage="scan", op="x", params={"width": 16}),
        Node(id="r", stage="rerank", op="x", params={"width": 32},
             inputs=("s",)),
    ), output="r")
    with pytest.raises(PlanError, match="widen"):
        plan_mod.validate(p)

    # symbolic widths carry the same contract: "shortlist" over "k"
    p = Plan(name="widen-sym", nodes=(
        Node(id="s", stage="scan", op="x", params={"width": "k"}),
        Node(id="r", stage="rerank", op="x",
             params={"width": "shortlist"}, inputs=("s",)),
    ), output="r")
    with pytest.raises(PlanError, match="widens"):
        plan_mod.validate(p)


def test_validate_rejects_malformed_graphs():
    with pytest.raises(PlanError, match="duplicate"):
        plan_mod.validate(Plan(name="d", nodes=(_scan("a"), _scan("a")),
                               output="a"))
    with pytest.raises(PlanError, match="unknown stage"):
        plan_mod.validate(Plan(name="st", nodes=(
            Node(id="a", stage="warp", op="x"),), output="a"))
    with pytest.raises(PlanError, match="unknown input"):
        plan_mod.validate(Plan(name="in", nodes=(
            Node(id="a", stage="scan", op="x", inputs=("ghost",)),),
            output="a"))
    with pytest.raises(PlanError, match="not a node"):
        plan_mod.validate(Plan(name="out", nodes=(_scan("a"),),
                               output="zzz"))
    with pytest.raises(PlanError, match="do not feed"):
        plan_mod.validate(Plan(name="dead", nodes=(
            _scan("a"), _scan("b")), output="a"))
    with pytest.raises(PlanError, match="candidate-producing"):
        plan_mod.validate(Plan(name="outf", nodes=(
            Node(id="f", stage="filter", op="bitset"),), output="f"))
    with pytest.raises(PlanError, match="width"):
        plan_mod.validate(Plan(name="w", nodes=(
            Node(id="a", stage="scan", op="x",
                 params={"width": "huge"}),), output="a"))


def test_split_at_merge_produces_valid_subplans():
    p = plan_mod.sharded_ivf_pq_plan(8, 32, 32, tail="codes")
    head, tail = plan_mod.split_at_merge(p)
    plan_mod.validate(head)
    assert tail is not None
    plan_mod.validate(tail)
    # the tail re-enters on an identity seed carrying the cut's width
    seed = [n for n in tail.nodes if n.op == "identity"]
    assert len(seed) == 1
    # a tail-less pipeline splits into (whole plan, None)
    head2, tail2 = plan_mod.split_at_merge(
        plan_mod.sharded_ivf_pq_plan(8, 32, 8))
    assert tail2 is None
    plan_mod.validate(head2)


# ---------------------------------------------------------------------------
# plan-vs-legacy bitwise matrix (serve dispatch vs library entry points)
# ---------------------------------------------------------------------------

_N, _DIM, _K, _M = 768, 32, 8, 24


def _data(seed=7, n=_N, dim=_DIM, m=_M):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, dim)).astype(np.float32),
            rng.standard_normal((m, dim)).astype(np.float32))


def _keep_filter(n, drop_ids):
    bs = Bitset(n)
    if len(drop_ids):
        bs.set(np.asarray(drop_ids, np.int64), False)
    return BitsetFilter(bs)


_MATRIX = {
    # algo key -> (build_params, search_params, refine_ratio)
    "brute_force": (None, None, 1),
    "ivf_flat": (ivf_flat.IndexParams(n_lists=8, metric="sqeuclidean"),
                 ivf_flat.SearchParams(n_probes=4), 1),
    "ivf_pq": (ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                  metric="sqeuclidean"),
               ivf_pq.SearchParams(n_probes=4), 1),
    # rabitq cache + dataset kept => the refined_tiered serving plan
    # (first-stage sign-bit scan + exact-tier rerank)
    "rabitq": (ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                  metric="sqeuclidean",
                                  cache_dtype="rabitq"),
               ivf_pq.SearchParams(n_probes=4), 4),
}


def _legacy(algo, h, q, k, prefilter, dataset):
    """The pre-plan dispatch: the library entry point the serve adapter
    hand-wired before ISSUE 20, on the SAME index object the handle
    serves."""
    if algo == "brute_force":
        return brute_force.search(h.index, q, k, prefilter=prefilter)
    if algo == "ivf_flat":
        return ivf_flat.search(h.search_params, h.index, q, k,
                               prefilter=prefilter)
    if algo == "ivf_pq":
        return ivf_pq.search(h.search_params, h.index, q, k,
                             prefilter=prefilter)
    assert algo == "rabitq"
    return ivf_pq.search_refined(h.search_params, h.index, q, k,
                                 refine_ratio=h.pipeline_rr(),
                                 prefilter=prefilter, dataset=dataset)


@pytest.mark.parametrize("algo", sorted(_MATRIX))
def test_plan_vs_legacy_bitwise_matrix(algo):
    """Serving through the compiled plan is byte-identical — distances
    AND ids — to the legacy library dispatch, with and without
    tombstones and user prefilters composed in."""
    bp, sp, rr = _MATRIX[algo]
    x, q = _data()
    serve_algo = "ivf_pq" if algo == "rabitq" else algo
    drop = np.arange(0, _N, 5)      # user prefilter: every 5th row
    dead = np.arange(3, _N, 7)      # tombstones: every 7th from 3

    with serve.Server(serve.ServeParams(max_batch_rows=32,
                                        max_wait_ms=1.0, max_k=_K)) as srv:
        srv.create_index("ix", x, algo=serve_algo, build_params=bp,
                         search_params=sp, refine_ratio=rr, warmup=False)
        h = srv.registry.get("ix").handle

        cases = [
            ("plain", None, []),
            ("prefilter", _keep_filter(_N, drop), []),
        ]
        for label, filt, tomb in cases:
            sd, si = srv.search(q, _K, index="ix", prefilter=filt)
            ld, li = _legacy(algo, h, q, _K, filt, x)
            assert np.array_equal(np.asarray(si), np.asarray(li)), label
            assert np.array_equal(np.asarray(sd), np.asarray(ld)), label

        # tombstones: serve composes the delete mask; legacy composes
        # the equivalent keep-bitset explicitly
        srv.delete(dead, index="ix")
        for label, user_drop in [("tombstone", []),
                                 ("tombstone+prefilter", drop)]:
            filt = None if not len(user_drop) \
                else _keep_filter(_N, user_drop)
            both = np.union1d(dead, np.asarray(user_drop, np.int64)) \
                if len(user_drop) else dead
            sd, si = srv.search(q, _K, index="ix", prefilter=filt)
            ld, li = _legacy(algo, h, q, _K, _keep_filter(_N, both), x)
            assert np.array_equal(np.asarray(si), np.asarray(li)), label
            assert np.array_equal(np.asarray(sd), np.asarray(ld)), label


def test_plan_vs_legacy_across_upsert_compact_swap():
    """An upsert + compact hot-swap recompiles the successor
    generation's plans; the post-swap serving path stays bitwise
    against legacy dispatch on the swapped-in index, including
    tombstones laid down after the swap."""
    x, q = _data(seed=13)
    bp = ivf_pq.IndexParams(n_lists=8, pq_dim=8, metric="sqeuclidean")
    sp = ivf_pq.SearchParams(n_probes=4)
    extra = np.random.default_rng(14).standard_normal(
        (16, _DIM)).astype(np.float32)

    with serve.Server(serve.ServeParams(max_batch_rows=32,
                                        max_wait_ms=1.0, max_k=_K)) as srv:
        srv.create_index("ix", x, algo="ivf_pq", build_params=bp,
                         search_params=sp, warmup=False)
        g1 = srv.registry.get("ix")
        srv.upsert(extra, np.arange(_N, _N + 16), index="ix")
        srv.compact(index="ix", wait=True)
        g2 = srv.registry.get("ix")
        assert g2.handle is not g1.handle   # the swap published a successor
        h = g2.handle
        n2 = _N + 16

        sd, si = srv.search(q, _K, index="ix")
        ld, li = ivf_pq.search(h.search_params, h.index, q, _K)
        assert np.array_equal(np.asarray(si), np.asarray(li))
        assert np.array_equal(np.asarray(sd), np.asarray(ld))

        dead = np.arange(0, n2, 9)
        srv.delete(dead, index="ix")
        sd, si = srv.search(q, _K, index="ix")
        ld, li = ivf_pq.search(h.search_params, h.index, q, _K,
                               prefilter=_keep_filter(n2, dead))
        assert np.array_equal(np.asarray(si), np.asarray(li))
        assert np.array_equal(np.asarray(sd), np.asarray(ld))


# ---------------------------------------------------------------------------
# sharded rabitq: worker subplan + router tail vs single-process
# ---------------------------------------------------------------------------


def test_sharded_rabitq_bitwise_vs_single_process(eight_device_mesh):
    """PR 10 leftover: rabitq-cached shards route through the per-shard
    first-stage subplan + router-side codes rerank tail — bitwise
    (ids AND distances) against single-process ``search_refined`` at
    exhaustive probing."""
    from raft_tpu.comms import sharded

    rng = np.random.default_rng(0)
    n, dim, k = 2048, 32, 10
    x = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((24, dim)).astype(np.float32)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, metric="sqeuclidean",
                           cache_dtype="rabitq"), x)
    sp = ivf_pq.SearchParams(n_probes=8)

    rd, ri = ivf_pq.search_refined(sp, idx, q, k, refine_ratio=4)
    sd, si = sharded.sharded_ivf_pq_search(sp, idx, q, k,
                                           eight_device_mesh,
                                           refine_ratio=4)
    assert np.array_equal(np.asarray(ri), np.asarray(si))
    assert np.array_equal(np.asarray(rd), np.asarray(sd))

    # refine_ratio=1 serves the sign-bit estimates directly
    pd, pi = sharded.sharded_ivf_pq_search(sp, idx, q, k,
                                           eight_device_mesh,
                                           refine_ratio=1)
    assert np.asarray(pi).shape == (24, k)

    # a rerank_source swaps the codes tail for the exact tiered tail —
    # also bitwise against the single-process dataset rerank
    td, ti = sharded.sharded_ivf_pq_search(sp, idx, q, k,
                                           eight_device_mesh,
                                           refine_ratio=4,
                                           rerank_source=x)
    xd, xi = ivf_pq.search_refined(sp, idx, q, k, refine_ratio=4,
                                   dataset=x)
    assert np.array_equal(np.asarray(ti), np.asarray(xi))
    assert np.array_equal(np.asarray(td), np.asarray(xd))

    # degraded answers still compose: the pre-merge hook masks invalid
    # lanes before the collective, coverage reports the healthy fraction
    _, pii, cov = sharded.sharded_ivf_pq_search(
        sp, idx, q, k, eight_device_mesh, refine_ratio=4,
        partial_ok=True)
    assert float(np.asarray(cov)) == 1.0
    assert np.array_equal(np.asarray(pii), np.asarray(ri))


# ---------------------------------------------------------------------------
# hybrid dense+sparse score_fuse plan (ROADMAP 6(a))
# ---------------------------------------------------------------------------


def _hybrid_rows(count, dd, vocab, r, density=0.15):
    dense = r.standard_normal((count, dd)).astype(np.float32)
    sp = r.standard_normal((count, vocab)).astype(np.float32)
    sp[r.random((count, vocab)) > density] = 0.0
    return np.concatenate([dense, sp], axis=1)


def test_hybrid_plan_recall_vs_fused_oracle():
    from raft_tpu.neighbors import hybrid

    rng = np.random.default_rng(1)
    n, dd, vocab, k, m = 600, 16, 64, 10, 24
    x = _hybrid_rows(n, dd, vocab, rng, density=0.12)
    q = _hybrid_rows(m, dd, vocab, rng, density=0.2)
    wd, ws = 0.7, 1.3
    idx = hybrid.build(
        hybrid.IndexParams(dense_dim=dd, w_dense=wd, w_sparse=ws), x)
    d, i = hybrid.search(hybrid.SearchParams(fuse_expand=8), idx, q, k)
    d, i = np.asarray(d), np.asarray(i)

    fused = wd * (q[:, :dd] @ x[:, :dd].T) + ws * (q[:, dd:] @ x[:, dd:].T)
    oracle = np.argsort(-fused, axis=1)[:, :k]
    rec = np.mean([len(set(i[r_]) & set(oracle[r_])) / k
                   for r_ in range(m)])
    assert rec > 0.95
    # returned scores ARE the fused scores of the returned ids
    assert np.max(np.abs(d - np.take_along_axis(fused, i, axis=1))) < 1e-4

    # prefilter composes into BOTH legs upstream of the fuse
    filt = _keep_filter(n, np.arange(0, n, 3))
    _, fi = hybrid.search(hybrid.SearchParams(fuse_expand=8), idx, q, k,
                          prefilter=filt)
    assert not np.any(np.asarray(fi) % 3 == 0)


def test_hybrid_served_end_to_end():
    """The score_fuse plan serves through the normal batcher/registry/
    tombstone machinery: recall vs the fused numpy oracle holds before
    and after delete + upsert traffic, and deleted rows never
    resurface."""
    from raft_tpu.neighbors import hybrid

    rng = np.random.default_rng(3)
    n, dd, vocab, k = 320, 12, 48, 6
    x = _hybrid_rows(n, dd, vocab, rng)
    q = _hybrid_rows(16, dd, vocab, rng)
    wd, ws = 0.8, 1.2

    def fused_oracle(rows, qq):
        return (wd * (qq[:, :dd] @ rows[:, :dd].T)
                + ws * (qq[:, dd:] @ rows[:, dd:].T))

    with serve.Server(serve.ServeParams(max_batch_rows=16,
                                        max_wait_ms=1.0, max_k=8)) as srv:
        srv.create_index(
            "h", x, algo="hybrid",
            build_params=hybrid.IndexParams(dense_dim=dd, w_dense=wd,
                                            w_sparse=ws))
        _, i = srv.search(q, k, index="h")
        oracle = fused_oracle(x, q)
        oids = np.argsort(-oracle, axis=1)[:, :k]
        rec = np.mean([len(set(i[r]) & set(oids[r])) / k
                       for r in range(q.shape[0])])
        assert rec > 0.95

        srv.delete(np.asarray(oids[:, 0]), index="h")
        new_rows = _hybrid_rows(8, dd, vocab, rng)
        srv.upsert(new_rows, np.arange(n, n + 8), index="h")
        _, i2 = srv.search(q, k, index="h")
        all_rows = np.concatenate([x, new_rows], axis=0)
        o2 = fused_oracle(all_rows, q)
        o2[:, oids[:, 0]] = -np.inf          # deletes are global
        oids2 = np.argsort(-o2, axis=1)[:, :k]
        rec2 = np.mean([len(set(i2[r]) & set(oids2[r])) / k
                        for r in range(q.shape[0])])
        assert rec2 > 0.95
        assert not any(oids[r, 0] in set(i2[r])
                       for r in range(q.shape[0]))


# ---------------------------------------------------------------------------
# zero steady-state retraces (GL007, serving edition)
# ---------------------------------------------------------------------------


def test_serve_plan_traffic_zero_steady_state_retraces():
    """Warmup walks the compiled plans over the (bucket, k, rung)
    ladder; a mixed-size post-warmup traffic stream with tombstones
    and prefilters must not grow ANY tracked trace cache."""
    x, _ = _data(seed=21)
    rng = np.random.default_rng(22)
    bp = ivf_pq.IndexParams(n_lists=8, pq_dim=8, metric="sqeuclidean")
    sp = ivf_pq.SearchParams(n_probes=4)

    with serve.Server(serve.ServeParams(max_batch_rows=32,
                                        max_wait_ms=1.0, max_k=_K)) as srv:
        srv.create_index("ix", x, algo="ivf_pq", build_params=bp,
                         search_params=sp, warmup=True)
        filt = _keep_filter(_N, np.arange(0, _N, 11))
        # settle pass: first traffic after warmup may pay one-time
        # shape visits (e.g. the composed-filter upload)
        for m in (1, 3, 8, 16):
            qq = rng.standard_normal((m, _DIM)).astype(np.float32)
            srv.search(qq, _K, index="ix")
            srv.search(qq, _K, index="ix", prefilter=filt)
        srv.delete(np.arange(0, _N, 13), index="ix")
        srv.search(rng.standard_normal((4, _DIM)).astype(np.float32),
                   _K, index="ix")

        before = serve.trace_cache_sizes()
        for m in (2, 5, 7, 12, 16, 1, 9):
            qq = rng.standard_normal((m, _DIM)).astype(np.float32)
            srv.search(qq, _K, index="ix")
            srv.search(qq, _K, index="ix", prefilter=filt)
        after = serve.trace_cache_sizes()
        growth = {kk: after[kk] - before.get(kk, 0)
                  for kk in after if after[kk] != before.get(kk, 0)}
        assert not growth, f"steady-state retraces: {growth}"
