"""Brute-force KNN vs naive oracle — analog of the reference's
tiled_brute_force/fused_l2_knn tests (cpp/test/neighbors/)."""

import numpy as np
import pytest

from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, knn_merge_parts, refine
from tests.oracles import eval_recall, naive_knn


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "inner_product", "cosine", "l1"])
def test_brute_force_exact(rng, metric):
    n, m, d, k = 700, 40, 32, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    idx = brute_force.build(x, metric)
    dist, ind = brute_force.search(idx, q, k)
    _, want = naive_knn(q, x, k, metric)
    assert eval_recall(np.asarray(ind), want) > 0.99


def test_brute_force_tiled_matches_full(rng):
    n, m, d, k = 1000, 16, 24, 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    idx = brute_force.build(x, "sqeuclidean")
    d_full, i_full = brute_force.search(idx, q, k, tile_n=1000)
    d_tile, i_tile = brute_force.search(idx, q, k, tile_n=128)
    np.testing.assert_array_equal(np.asarray(i_full), np.asarray(i_tile))
    np.testing.assert_allclose(np.asarray(d_full), np.asarray(d_tile), rtol=1e-5)


def test_brute_force_prefilter(rng):
    n, m, d, k = 300, 10, 16, 5
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    allowed = rng.random(n) < 0.5
    bs = Bitset.from_dense(allowed)
    idx = brute_force.build(x, "sqeuclidean")
    _, ind = brute_force.search(idx, q, k, prefilter=bs)
    ind = np.asarray(ind)
    assert allowed[ind.ravel()].all()
    # oracle on the filtered subset
    sub = np.where(allowed)[0]
    _, want_sub = naive_knn(q, x[sub], k)
    want = sub[want_sub]
    assert eval_recall(ind, want) > 0.99


@pytest.mark.parametrize("tile_n", [300, 64])  # whole-dataset + scan paths
def test_brute_force_prefilter_out_of_range_modes(rng, tile_n):
    """out_of_range semantics (ISSUE 5 satellite): a filter narrower
    than the dataset drops ids >= n_bits by default (allow-list), while
    "keep" treats them as kept (tombstone keep-mask over an index
    extended after the filter was built)."""
    from raft_tpu.neighbors.common import BitsetFilter

    n, m, d, k = 300, 10, 16, 5
    n_old = 180
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    keep = rng.random(n_old) < 0.5       # filter over the OLD rows only
    narrow = Bitset.from_dense(keep)
    idx = brute_force.build(x, "sqeuclidean")

    # default "drop": out-of-range (new) rows rejected
    _, i_drop = brute_force.search(idx, q, k, prefilter=narrow,
                                   tile_n=tile_n)
    i_drop = np.asarray(i_drop)
    assert (i_drop < n_old).all() and keep[i_drop.ravel()].all()

    # "keep": new rows eligible — must equal the materialized full mask
    d_keep, i_keep = brute_force.search(
        idx, q, k, prefilter=BitsetFilter(narrow, out_of_range="keep"),
        tile_n=tile_n)
    full = Bitset.from_dense(np.concatenate([keep,
                                             np.ones(n - n_old, bool)]))
    d_ref, i_ref = brute_force.search(idx, q, k, prefilter=full,
                                      tile_n=tile_n)
    np.testing.assert_array_equal(np.asarray(i_keep), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_keep), np.asarray(d_ref))


def test_bitset_filter_out_of_range_validation():
    from raft_tpu.neighbors.common import BitsetFilter

    with pytest.raises(ValueError, match="out_of_range"):
        BitsetFilter(Bitset(8), out_of_range="maybe")


def test_resolve_filter_bits_caches_materialized_keep():
    """A keep-mode filter reused across searches must pay the resize's
    device ops once: the materialized bitset is cached on the filter,
    keyed by (bound, Bitset._version) so an in-place mutation or a
    different index width invalidates it."""
    from raft_tpu.neighbors.common import BitsetFilter, resolve_filter_bits

    bits = Bitset.from_dense(np.array([True, False, True, True]))
    filt = BitsetFilter(bits, out_of_range="keep")
    a = resolve_filter_bits(filt, 10)
    assert a.n_bits == 10
    assert resolve_filter_bits(filt, 10) is a          # cache hit
    b = resolve_filter_bits(filt, 12)                  # wider index: miss
    assert b.n_bits == 12 and b is not a
    bits.set(1, True)                                  # in-place mutation
    c = resolve_filter_bits(filt, 12)                  # version bump: miss
    assert c is not b
    assert bool(np.asarray(c.to_dense())[1])
    # drop-mode and wide-enough filters bypass materialization entirely
    assert resolve_filter_bits(BitsetFilter(bits), 10) is bits
    assert resolve_filter_bits(filt, 4) is bits


def test_knn_one_shot_and_serialize(rng, tmp_path):
    x = rng.standard_normal((200, 8)).astype(np.float32)
    q = rng.standard_normal((7, 8)).astype(np.float32)
    d1, i1 = brute_force.knn(q, x, 4)
    p = str(tmp_path / "bf.bin")
    brute_force.save(p, brute_force.build(x, "sqeuclidean"))
    idx = brute_force.load(p)
    d2, i2 = brute_force.search(idx, q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_knn_merge_parts(rng):
    # split the dataset in 3 parts, search each, merge -> must equal global
    n, m, d, k = 600, 12, 16, 9
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    parts = np.split(x, 3)
    pd, pi, trans = [], [], []
    off = 0
    for part in parts:
        dd, ii = brute_force.knn(q, part, k)
        pd.append(np.asarray(dd))
        pi.append(np.asarray(ii))
        trans.append(off)
        off += part.shape[0]
    md, mi = knn_merge_parts(np.stack(pd), np.stack(pi), k, translations=np.asarray(trans))
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(mi), want) > 0.99


def test_refine(rng):
    n, m, d, k = 500, 20, 16, 5
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    # candidates: true top-20 shuffled + some noise, with invalid (-1) slots
    _, cand = naive_knn(q, x, 20)
    cand = cand.astype(np.int32)
    cand[:, -2:] = -1
    dist, ind = refine(x, q, cand, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(ind), want) > 0.99
    assert (np.asarray(ind) >= 0).all()


def test_bin_io(rng, tmp_path):
    from raft_tpu.bench import read_bin, write_bin

    arr = rng.standard_normal((10, 4)).astype(np.float32)
    p = str(tmp_path / "x.fbin")
    write_bin(p, arr)
    out = read_bin(p)
    np.testing.assert_array_equal(np.asarray(out), arr)
    sub = read_bin(p, rows=(2, 5))
    np.testing.assert_array_equal(np.asarray(sub), arr[2:7])


def test_fast_path_recall(rng):
    n, m, d, k = 2000, 100, 64, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    index = brute_force.build(x, "sqeuclidean")
    _, want = naive_knn(q, x, k)
    _, idx = brute_force.search(index, q, k, fast=True)
    assert eval_recall(np.asarray(idx), want) > 0.95


def test_fast_path_respects_prefilter(rng):
    # regression: fast=True must not resurrect prefiltered-out rows during
    # the unfiltered refine phase
    from raft_tpu.core.bitset import Bitset

    n, m, d, k = 100, 8, 16, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    allowed = np.zeros(n, bool)
    allowed[:15] = True  # fewer allowed rows than the candidate pool
    bits = Bitset.from_dense(allowed)
    index = brute_force.build(x, "sqeuclidean")
    _, idx = brute_force.search(index, q, k, prefilter=bits, fast=True)
    idx = np.asarray(idx)
    valid = idx >= 0
    assert allowed[idx[valid]].all()
    # the 15 allowed rows must fill the first slots exactly like fast=False
    _, idx_slow = brute_force.search(index, q, k, prefilter=bits, fast=False)
    d2 = ((q[:, None, :] - x[None, :15, :]) ** 2).sum(-1)
    want = np.argsort(d2, axis=1)[:, :k]
    for r in range(m):
        assert set(idx[r][idx[r] >= 0]) <= set(range(15))


def test_bf16_inputs_stay_bf16(rng):
    # regression: bf16 queries/dataset must not be silently promoted to f32
    # before the candidate matmul
    import jax.numpy as jnp
    from raft_tpu.neighbors.brute_force import _search
    from raft_tpu.distance.types import DistanceType

    x = jnp.asarray(rng.standard_normal((64, 16)), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((8, 16)), jnp.bfloat16)
    import jax

    jaxpr = jax.make_jaxpr(
        lambda q, x: _search(q, x, None, None, None, 5,
                             int(DistanceType.L2Expanded), 2.0, 64)
    )(q, x)
    text = str(jaxpr)
    # the dot_general must consume bf16 operands
    assert "bf16" in text.split("dot_general")[1][:400]
