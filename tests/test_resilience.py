"""Resilience layer: fault-injection matrix, OOM degradation ladder,
checkpointed streaming resume, and graceful shard degradation — all on
the CPU tier-1 platform via the deterministic harness
(raft_tpu/resilience/faultinject.py; docs/resilience.md)."""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import resilience, tuning
from raft_tpu.core.interruptible import Interruptible, InterruptedException
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.neighbors.stream import search_host_array
from raft_tpu.resilience import checkpoint, degrade, errors, faultinject
from tests.oracles import naive_knn

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    tuning.reload()
    yield
    faultinject.clear()
    tuning.reload()


# ---------------------------------------------------------------------------
# classification + retry executor
# ---------------------------------------------------------------------------


def test_classify_kinds():
    assert resilience.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 3221225472 bytes"
    )) == resilience.OOM
    assert resilience.classify(RuntimeError(
        "UNAVAILABLE: connection reset by peer")) == resilience.TRANSIENT
    assert resilience.classify(ValueError("shape mismatch")) == resilience.FATAL
    assert resilience.classify(MemoryError()) == resilience.OOM
    assert resilience.classify(InterruptedException("x")) == resilience.INTERRUPTED
    import subprocess

    assert resilience.classify(
        subprocess.TimeoutExpired("cmd", 5)) == resilience.DEAD_BACKEND
    assert resilience.classify(
        faultinject.InjectedOOM("RESOURCE_EXHAUSTED: injected")
    ) == resilience.OOM
    assert resilience.classify(
        faultinject.InjectedDeadBackend("x")) == resilience.DEAD_BACKEND
    assert resilience.classify(
        resilience.TransientError("stage flaked")) == resilience.TRANSIENT


def test_classify_text():
    assert resilience.classify_text("... RESOURCE_EXHAUSTED ...") == resilience.OOM
    assert resilience.classify_text("UNAVAILABLE: socket closed") == resilience.TRANSIENT
    assert resilience.classify_text("assert failed") == resilience.FATAL


def test_run_retries_transient_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise resilience.TransientError("blip")
        return "ok"

    assert resilience.run(flaky, retries=3, backoff_s=0.001) == "ok"
    assert len(calls) == 3


def test_run_retry_budget_exhausted():
    def always():
        raise resilience.TransientError("blip")

    with pytest.raises(resilience.TransientError):
        resilience.run(always, retries=1, backoff_s=0.001)


def test_run_fatal_not_retried():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("bug")

    with pytest.raises(ValueError):
        resilience.run(boom, retries=3, backoff_s=0.001)
    assert len(calls) == 1


def test_run_oom_not_retried_by_default():
    calls = []

    def oom():
        calls.append(1)
        raise faultinject.InjectedOOM("RESOURCE_EXHAUSTED: injected")

    with pytest.raises(faultinject.InjectedOOM):
        resilience.run(oom, retries=3, backoff_s=0.001)
    assert len(calls) == 1


def test_run_deadline_exceeded():
    def always():
        raise resilience.TransientError("blip")

    t0 = time.monotonic()
    with pytest.raises(resilience.DeadlineExceededError):
        resilience.run(always, retries=50, backoff_s=0.2, deadline_s=0.3)
    assert time.monotonic() - t0 < 5.0


def test_run_dead_backend_probes_then_retries():
    # on CPU the liveness probe answers, so a one-shot dead fault recovers
    calls = []

    def once_dead():
        calls.append(1)
        if len(calls) == 1:
            raise faultinject.InjectedDeadBackend("injected dead-backend")
        return 7

    assert resilience.run(once_dead, retries=2, backoff_s=0.001) == 7
    assert len(calls) == 2


def test_run_cancelled_token_stops():
    tok = Interruptible()
    tok.cancel()
    with pytest.raises(InterruptedException):
        resilience.run(lambda: 1, token=tok)


def test_backend_alive_on_cpu():
    assert resilience.backend_alive(timeout_s=30.0)


# ---------------------------------------------------------------------------
# fault-injection grammar
# ---------------------------------------------------------------------------


def test_faultinject_grammar():
    specs = faultinject.parse("oom@chunk:3,dead@stage:search,shard@rank:2")
    assert [(s.kind, s.scope, s.arg) for s in specs] == [
        ("oom", "chunk", "3"), ("dead", "stage", "search"),
        ("shard", "rank", "2"),
    ]
    (s,) = faultinject.parse("oom@chunk:1*2")
    assert s.remaining == 2
    (s,) = faultinject.parse("dead@stage:build.pass2#3")
    assert (s.scope, s.arg) == ("stage", "build.pass2#3")
    with pytest.raises(ValueError):
        faultinject.parse("dead@stage:build.pass2#x")
    with pytest.raises(ValueError):
        faultinject.parse("oops@chunk:3")
    with pytest.raises(ValueError):
        faultinject.parse("oom@list:3")
    with pytest.raises(ValueError):
        faultinject.parse("oom@chunk:abc")


def test_faultinject_fires_once_per_spec():
    with faultinject.inject("oom@chunk:1"):
        faultinject.check(stage="s", chunk=0)          # no match
        with pytest.raises(faultinject.InjectedOOM):
            faultinject.check(stage="s", chunk=1)
        faultinject.check(stage="s", chunk=1)          # consumed
    faultinject.check(stage="s", chunk=1)              # plan cleared


def test_faultinject_proc_rpc_grammar():
    specs = faultinject.parse("dead@proc:2,slow@proc:1*3,drop@rpc:search")
    assert [(s.kind, s.scope, s.arg, s.remaining) for s in specs] == [
        ("dead", "proc", "2", 1), ("slow", "proc", "1", 3),
        ("drop", "rpc", "search", 1),
    ]
    with pytest.raises(ValueError):
        faultinject.parse("slow@chunk:1")     # slow is proc-only
    with pytest.raises(ValueError):
        faultinject.parse("drop@proc:1")      # drop is rpc-only
    with pytest.raises(ValueError):
        faultinject.parse("oom@proc:1")       # proc takes dead/slow only
    with pytest.raises(ValueError):
        faultinject.parse("dead@proc:x")      # proc rank must be int


def test_faultinject_proc_action_one_shot_and_repeat():
    with faultinject.inject("dead@proc:2,slow@proc:1*2"):
        assert faultinject.proc_action(0) is None
        assert faultinject.proc_action(1) == "slow"
        assert faultinject.proc_action(1) == "slow"
        assert faultinject.proc_action(1) is None      # count exhausted
        assert faultinject.proc_action(2) == "die"
        assert faultinject.proc_action(2) is None      # one-shot
    assert faultinject.proc_action(2) is None          # plan cleared


def test_faultinject_rpc_drop_consumed():
    with faultinject.inject("drop@rpc:search*2"):
        assert not faultinject.rpc_dropped("prepare")  # method-scoped
        assert faultinject.rpc_dropped("search")
        assert faultinject.rpc_dropped("search")
        assert not faultinject.rpc_dropped("search")   # count exhausted
    assert not faultinject.rpc_dropped("search")


def test_faultinject_proc_scopes_never_raise_from_check():
    # proc/rpc specs are queried, not raised: check() must ignore them
    with faultinject.inject("dead@proc:0,slow@proc:0,drop@rpc:search"):
        faultinject.check(stage="search", chunk=0)


def test_run_probe_clamped_to_deadline(monkeypatch):
    # a hanging probe (the dead-axon init-hang mode) must not stall the
    # retry loop past deadline_s: run() clamps the probe wait to the
    # remaining deadline, and the probe timeout classifies dead_backend
    probe_waits = []

    def fake_alive(timeout_s=30.0):
        probe_waits.append(timeout_s)
        time.sleep(min(timeout_s, 5.0))   # hung probe honoring its bound
        return False

    monkeypatch.setattr(errors, "backend_alive", fake_alive)

    def dead():
        raise faultinject.InjectedDeadBackend("injected dead-backend")

    t0 = time.monotonic()
    with pytest.raises(errors.DeadBackendError):
        resilience.run(dead, retries=3, backoff_s=0.01, deadline_s=0.4,
                       probe_timeout_s=30.0)
    assert time.monotonic() - t0 < 2.0     # NOT the 30s probe default
    assert probe_waits and probe_waits[0] <= 0.4
    assert resilience.classify(errors.DeadBackendError("x")) == \
        resilience.DEAD_BACKEND


def test_faultinject_flap_and_delay_grammar():
    specs = faultinject.parse(
        "flap@proc:1#after:10*2,dead@proc:0#after:20,slow@proc:2*3")
    assert [(s.kind, s.arg, s.delay, s.remaining) for s in specs] == [
        ("flap", "1", 10, 2), ("dead", "0", 20, 1), ("slow", "2", 0, 3),
    ]
    # render round-trips the full grammar (the respawn rewrite depends
    # on it)
    again = faultinject.parse(",".join(s.render() for s in specs))
    assert [(s.kind, s.arg, s.delay, s.remaining) for s in again] == \
        [(s.kind, s.arg, s.delay, s.remaining) for s in specs]
    with pytest.raises(ValueError):
        faultinject.parse("flap@stage:x")        # flap is proc-only
    with pytest.raises(ValueError):
        faultinject.parse("flap@rpc:search")
    with pytest.raises(ValueError):
        faultinject.parse("dead@proc:1#later:3")  # only #after:N
    with pytest.raises(ValueError):
        faultinject.parse("dead@proc:1#after:x")
    with pytest.raises(ValueError):
        faultinject.parse("dead@proc:1#after:-2")


def test_faultinject_delayed_proc_action_arms_after_n():
    with faultinject.inject("dead@proc:0#after:2"):
        assert faultinject.proc_action(0) is None      # survives 1
        assert faultinject.proc_action(1) is None      # other rank
        assert faultinject.proc_action(0) is None      # survives 2
        assert faultinject.proc_action(0) == "die"     # armed
        assert faultinject.proc_action(0) is None      # consumed


def test_faultinject_flap_fires_per_count():
    with faultinject.inject("flap@proc:1*2"):
        assert faultinject.proc_action(1) == "die"
        assert faultinject.proc_action(1) == "die"
        assert faultinject.proc_action(1) is None      # budget spent


def test_faultinject_respawned_spec_rewrite():
    spec = "flap@proc:1#after:3*3,dead@proc:0#after:20,slow@proc:2*2"
    # rank 1's first respawn: one death charged, delay kept
    out = faultinject.respawned_spec(spec, rank=1, deaths=1)
    (flap,) = [s for s in faultinject.parse(out) if s.kind == "flap"]
    assert (flap.remaining, flap.delay) == (2, 3)
    # budget exhausted: the flap spec vanishes — the worker holds
    out = faultinject.respawned_spec(spec, rank=1, deaths=3)
    assert not any(s.kind == "flap" for s in faultinject.parse(out))
    # dead is permanent: the respawned incarnation dies at its FIRST
    # RPC (the #after delay modeled the first death only)
    out = faultinject.respawned_spec(spec, rank=0, deaths=1)
    (dead,) = [s for s in faultinject.parse(out) if s.kind == "dead"]
    assert (dead.remaining, dead.delay) == (1, 0)
    # other ranks' specs ride along verbatim
    (slow,) = [s for s in faultinject.parse(out) if s.kind == "slow"]
    assert (slow.arg, slow.remaining) == ("2", 2)
    assert faultinject.respawned_spec(None, 0, 1) is None
    assert faultinject.respawned_spec("flap@proc:0*1", 0, 1) is None


# ---------------------------------------------------------------------------
# full-jitter backoff (ISSUE 18)
# ---------------------------------------------------------------------------


def test_backoff_jitter_bounds_and_determinism():
    resilience.seed_jitter(42)
    try:
        a = [resilience.backoff_jitter_s(n, 0.1) for n in range(6)]
        resilience.seed_jitter(42)
        b = [resilience.backoff_jitter_s(n, 0.1) for n in range(6)]
        assert a == b                       # seeded => reproducible
        for n, s in enumerate(a):
            assert 0.0 <= s <= 0.1 * (2.0 ** n)
        # jitter=False returns the deterministic cap (legacy schedule)
        assert resilience.backoff_jitter_s(3, 0.1, jitter=False) == \
            pytest.approx(0.8)
        assert resilience.backoff_jitter_s(0, 0.0) == 0.0
    finally:
        resilience.seed_jitter(None)


def test_run_jittered_backoff_respects_deadline():
    # deadline math uses the UNJITTERED cap: a lucky small jitter draw
    # must not let the loop start an attempt it cannot afford
    resilience.seed_jitter(7)
    try:
        def always():
            raise resilience.TransientError("blip")

        t0 = time.monotonic()
        with pytest.raises(resilience.DeadlineExceededError):
            resilience.run(always, retries=50, backoff_s=0.2,
                           deadline_s=0.3)
        assert time.monotonic() - t0 < 5.0
        # and jitter=False restores the exact legacy sleep schedule
        calls = []

        def twice():
            calls.append(time.monotonic())
            if len(calls) < 3:
                raise resilience.TransientError("blip")
            return 9

        assert resilience.run(twice, retries=3, backoff_s=0.01,
                              jitter=False) == 9
        assert len(calls) == 3
    finally:
        resilience.seed_jitter(None)


def test_faultinject_env(monkeypatch):
    monkeypatch.setenv(faultinject.ENV_VAR, "transient@stage:probe")
    faultinject.clear()
    with pytest.raises(faultinject.InjectedTransient):
        faultinject.check(stage="probe")
    faultinject.check(stage="probe")                   # consumed
    monkeypatch.setenv(faultinject.ENV_VAR, "")
    faultinject.clear()
    assert not faultinject.active()


# ---------------------------------------------------------------------------
# tuning runtime budgets
# ---------------------------------------------------------------------------


def test_runtime_budget_records_min_and_clamps():
    assert tuning.runtime_budget("x") is None
    tuning.record_budget("x", 64)
    tuning.record_budget("x", 128)        # larger records keep the min
    assert tuning.runtime_budget("x") == 64
    assert tuning.budget("x", 512) == 64
    assert tuning.budget("x", 32) == 32   # never grows past the default
    tuning.reload()
    assert tuning.runtime_budget("x") is None


# ---------------------------------------------------------------------------
# checkpoint container
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip(tmp_path):
    ck = resilience.StreamCheckpoint(str(tmp_path))
    assert ck.load() is None
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    ck.save("p", 2, {"rows": 3}, {"a": arr}, fingerprint={"k": 10})
    phase, step, meta, arrays = ck.load(fingerprint={"k": 10})
    assert (phase, step, meta) == ("p", 2, {"rows": 3})
    assert np.array_equal(arrays["a"], arr)
    # manifest-only peek agrees without touching the blob
    assert ck.peek(fingerprint={"k": 10}) == ("p", 2, {"rows": 3})
    with pytest.raises(checkpoint.CheckpointMismatchError):
        ck.load(fingerprint={"k": 11})
    with pytest.raises(checkpoint.CheckpointMismatchError):
        ck.peek(fingerprint={"k": 11})
    # later saves garbage-collect older blobs
    ck.save("p", 3, {"rows": 4}, {"a": arr}, fingerprint={"k": 10})
    blobs = [f for f in os.listdir(tmp_path) if f.endswith(".bin")]
    assert blobs == ["state-3.bin"]
    ck.clear()
    assert ck.load() is None


# ---------------------------------------------------------------------------
# streaming fault matrix (brute_force / ivf_flat / ivf_pq x chunk boundary)
# ---------------------------------------------------------------------------

_N, _D, _M, _K = 600, 24, 300, 10
_BATCH = 64                          # -> 5 chunks over 300 queries


class _BF:
    """brute_force adapter for the module.search(sp, index, q, k) shape."""

    @staticmethod
    def search(sp, index, batch, k):
        return brute_force.search(index, batch, k)


@pytest.fixture(scope="module")
def stream_data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((_N, _D)).astype(np.float32)
    q = rng.standard_normal((_M, _D)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def stream_modules(stream_data):
    x, _ = stream_data
    flat = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4,
                             kmeans_trainset_fraction=1.0), x)
    pq = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                           kmeans_trainset_fraction=1.0), x)
    return {
        "brute_force": (_BF, None, brute_force.build(x)),
        "ivf_flat": (ivf_flat,
                     ivf_flat.SearchParams(n_probes=8, query_group=8), flat),
        "ivf_pq": (ivf_pq,
                   ivf_pq.SearchParams(n_probes=8, query_group=8), pq),
    }


@pytest.mark.parametrize("algo", ["brute_force", "ivf_flat", "ivf_pq"])
@pytest.mark.parametrize("chunk", [0, 2, 4])
def test_oom_ladder_matches_uninjected(stream_modules, stream_data, algo,
                                       chunk):
    """Injected OOM at every chunk boundary converges via the halving
    ladder to results identical to the fault-free run."""
    mod, sp, index = stream_modules[algo]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    with faultinject.inject(f"oom@chunk:{chunk}"):
        d, i = search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                                 backoff_s=0.001)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)
    assert tuning.runtime_budget("stream_batch_rows") == _BATCH // 2


@pytest.mark.parametrize("algo", ["brute_force", "ivf_flat", "ivf_pq"])
def test_dead_backend_mid_stage_recovers(stream_modules, stream_data, algo):
    """A dead backend mid-stage is probed (alive again on CPU: the
    injection is one-shot, like a bounced tunnel) and the batch retried;
    recovered results match the uninjected run."""
    mod, sp, index = stream_modules[algo]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    with faultinject.inject("dead@chunk:1"):
        d, i = search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                                 backoff_s=0.001)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


def test_oom_two_rungs_quarters_batch(stream_modules, stream_data):
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    with faultinject.inject("oom@chunk:1*3"):
        d, i = search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                                 backoff_s=0.001)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)
    # every re-dispatch of chunk 1 re-arms the spec: 64, 32, 16 all
    # struck, the 8-row rung survived
    assert tuning.runtime_budget("stream_batch_rows") == _BATCH // 8


def test_oom_at_min_rows_propagates(stream_modules, stream_data):
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    # more strikes than the ladder has rungs for one 64-row batch
    with faultinject.inject("oom@chunk:0*50"):
        with pytest.raises(faultinject.InjectedOOM):
            search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                              backoff_s=0.001)


def test_transient_retry_bitwise(stream_modules, stream_data):
    mod, sp, index = stream_modules["ivf_flat"]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    with faultinject.inject("transient@chunk:0,transient@chunk:3"):
        d, i = search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                                 backoff_s=0.001)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


# ---------------------------------------------------------------------------
# checkpointed streaming search
# ---------------------------------------------------------------------------


def test_search_ckpt_resume_bitwise(stream_modules, stream_data, tmp_path):
    """A job killed at an arbitrary chunk resumes to bitwise-identical
    results, skipping the chunks the checkpoint already covers."""
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    ckdir = str(tmp_path / "ck")
    with faultinject.inject("dead@chunk:3"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                              checkpoint_dir=ckdir, checkpoint_every=1,
                              retries=0)
    # the manifest proves 3 chunks (192 rows) completed before the kill
    import json

    manifest = json.load(open(os.path.join(ckdir, "manifest.json")))
    assert manifest["meta"]["rows_done"] == 3 * _BATCH
    d, i = search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                             checkpoint_dir=ckdir, resume=True)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


def test_search_resume_other_batch_size_bitwise(stream_modules, stream_data,
                                                tmp_path):
    """Host-array resume restarts AT the completed-row mark (start_row),
    so a different batch_rows still yields bitwise-identical output —
    per-query searches are row-independent."""
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    base_d, base_i = search_host_array(mod, sp, index, q, _K,
                                       batch_rows=_BATCH)
    ckdir = str(tmp_path / "ck2")
    with faultinject.inject("dead@chunk:2"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                              checkpoint_dir=ckdir, checkpoint_every=1,
                              retries=0)
    d, i = search_host_array(mod, sp, index, q, _K, batch_rows=48,
                             checkpoint_dir=ckdir, resume=True)
    assert np.array_equal(d, base_d)
    assert np.array_equal(i, base_i)


def test_search_stream_rejects_misaligned_iterator(stream_modules,
                                                   stream_data, tmp_path):
    """An iterator that cannot seek (the file path) re-produces batches
    from offset 0 at a DIFFERENT size than the checkpoint was written at
    — search_stream refuses rather than splice misaligned rows."""
    from raft_tpu.neighbors.stream import search_stream
    from raft_tpu.utils.batch import BatchLoadIterator

    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    ckdir = str(tmp_path / "ck3")

    def fn(batch):
        return mod.search(sp, index, batch, _K)

    with faultinject.inject("dead@chunk:2"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            search_stream(fn, BatchLoadIterator(q, _BATCH, pad_to_full=True),
                          q.shape[0], _K, checkpoint_dir=ckdir,
                          checkpoint_every=1, retries=0)
    with pytest.raises(ValueError, match="resume misalignment"):
        search_stream(fn, BatchLoadIterator(q, 48, pad_to_full=True),
                      q.shape[0], _K, checkpoint_dir=ckdir, resume=True)


# ---------------------------------------------------------------------------
# checkpointed build (ivf_pq.build_streamed)
# ---------------------------------------------------------------------------

_BN, _BD = 512, 16


def _build_batches(x, bs=64):
    def make():
        for s in range(0, x.shape[0], bs):
            yield jnp.asarray(x[s:s + bs])
    return make


def _assert_index_bitwise(a, b):
    for f in ("codes", "indices", "list_sizes", "rec_norms", "centers",
              "centers_rot", "rotation", "pq_centers"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


@pytest.fixture(scope="module")
def build_setup():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((_BN, _BD)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)
    base = ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                                 trainset=x)
    return x, params, base


@pytest.mark.parametrize("fault", ["dead@stage:build.pass1",
                                   "dead@chunk:3",
                                   "dead@stage:build.pass2#3"])
def test_build_stream_kill_resume_bitwise(build_setup, tmp_path, fault):
    """build_streamed killed mid-pass-1 (chunk:3 also lands there —
    pass-1 chunks come first) or mid-pass-2 (the stage#chunk spec, which
    exercises the donated-accumulator restore) resumes from the
    per-chunk checkpoint to a bitwise-identical index (quantizers are
    restored, never retrained)."""
    x, params, base = build_setup
    ckdir = str(tmp_path / "bck")
    with faultinject.inject(fault):
        with pytest.raises(faultinject.InjectedDeadBackend):
            ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                                  trainset=x, checkpoint_dir=ckdir,
                                  checkpoint_every=1)
    got = ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                                trainset=x, checkpoint_dir=ckdir,
                                checkpoint_every=1, resume=True)
    _assert_index_bitwise(base, got)


def test_build_stream_resume_rejects_other_config(build_setup, tmp_path):
    x, params, _ = build_setup
    ckdir = str(tmp_path / "bck2")
    with faultinject.inject("dead@chunk:2"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                                  trainset=x, checkpoint_dir=ckdir,
                                  checkpoint_every=1)
    import dataclasses

    other = dataclasses.replace(params, n_lists=16)
    with pytest.raises(checkpoint.CheckpointMismatchError):
        ivf_pq.build_streamed(other, _build_batches(x), _BN, _BD,
                              trainset=x, checkpoint_dir=ckdir,
                              checkpoint_every=1, resume=True)


# ---------------------------------------------------------------------------
# cooperative cancellation through the streaming loops
# ---------------------------------------------------------------------------


def test_cancel_stops_search(stream_modules, stream_data):
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    tok = Interruptible()
    tok.cancel()
    with pytest.raises(InterruptedException):
        search_host_array(mod, sp, index, q, _K, batch_rows=_BATCH,
                          token=tok)


def test_cancel_from_other_thread_stops_search(stream_modules, stream_data):
    mod, sp, index = stream_modules["brute_force"]
    _, q = stream_data
    tok = Interruptible()
    started = threading.Event()

    class _Slow:
        @staticmethod
        def search(sp_, index_, batch, k):
            started.set()
            time.sleep(0.05)
            return mod.search(sp_, index_, batch, k)

    result = {}

    def work():
        try:
            search_host_array(_Slow, sp, index, q, _K, batch_rows=32,
                              token=tok)
            result["out"] = "finished"
        except InterruptedException:
            result["out"] = "interrupted"

    t = threading.Thread(target=work)
    t.start()
    started.wait(10.0)
    tok.cancel()
    t.join(30.0)
    assert result.get("out") == "interrupted"


def test_cancel_stops_build():
    rng = np.random.default_rng(12)
    x = rng.standard_normal((_BN, _BD)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                                kmeans_trainset_fraction=1.0)
    tok = Interruptible()
    tok.cancel()
    with pytest.raises(InterruptedException):
        ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                              trainset=x, token=tok)


# ---------------------------------------------------------------------------
# CAGRA transient-buffer ladder
# ---------------------------------------------------------------------------


def test_shrinking_blocks_tail_oom_keeps_budget():
    """An OOM on a short tail block retries the tail at half size but
    must NOT shrink the process-wide budget to half-of-a-few-rows."""
    calls = []

    def fn(start, rows):
        calls.append((start, rows))
        return jnp.arange(start, start + rows)

    # blocks of 64 over 70 rows -> full block [0,64), tail [64,70);
    # strike the tail (chunk 1) with OOM
    with faultinject.inject("oom@chunk:1"):
        parts = list(degrade.run_shrinking_blocks(
            fn, 70, 64, budget_name="tail_test", stage="tail"))
    got = np.concatenate([np.asarray(p) for p in parts])
    assert np.array_equal(got, np.arange(70))
    # tail retried at 3 rows, but no budget recorded (full block never failed)
    assert tuning.runtime_budget("tail_test") is None
    # a FULL block failing must still record
    with faultinject.inject("oom@chunk:0"):
        parts = list(degrade.run_shrinking_blocks(
            fn, 70, 64, budget_name="tail_test2", stage="tail"))
    got = np.concatenate([np.asarray(p) for p in parts])
    assert np.array_equal(got, np.arange(70))
    assert tuning.runtime_budget("tail_test2") == 32


def test_cagra_detour_ladder_bitwise():
    from raft_tpu.neighbors import cagra

    rng = np.random.default_rng(13)
    graph = rng.integers(0, 200, (200, 8)).astype(np.int32)
    base = np.asarray(cagra._detour_counts(graph, 64, nodes_per_call=64))
    tuning.reload()
    with faultinject.inject("oom@chunk:1"):
        got = np.asarray(cagra._detour_counts(graph, 64, nodes_per_call=64))
    assert np.array_equal(base, got)
    assert tuning.runtime_budget("cagra_detour_rows") == 32


# ---------------------------------------------------------------------------
# graceful shard degradation (dropout at each rank) + auto-padding
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shard_data():
    rng = np.random.default_rng(17)
    x = rng.standard_normal((96, 16)).astype(np.float32)
    q = rng.standard_normal((7, 16)).astype(np.float32)
    return x, q


@pytest.mark.parametrize("rank", list(range(8)))
def test_sharded_knn_dropout_each_rank(shard_data, eight_device_mesh, rank):
    """One injected dead shard -> partial_ok results with coverage
    (S-1)/S, exactly equal to exact KNN over the surviving shards."""
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    k, rows = 5, x.shape[0] // 8
    with faultinject.inject(f"shard@rank:{rank}"):
        d, i, cov = sharded_knn(q, x, k, eight_device_mesh, partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    keep = np.ones(x.shape[0], bool)
    keep[rank * rows:(rank + 1) * rows] = False
    ids_map = np.nonzero(keep)[0]
    _, want = naive_knn(q, x[keep], k)
    assert np.array_equal(np.asarray(i), ids_map[want])


def test_sharded_knn_dropout_without_partial_ok_raises(shard_data,
                                                       eight_device_mesh):
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    with faultinject.inject("shard@rank:3"):
        with pytest.raises(resilience.ShardDropoutError):
            sharded_knn(q, x, 5, eight_device_mesh)


def test_sharded_knn_real_nan_shard_masked(shard_data, eight_device_mesh):
    """A real fault signature (NaN rows in one shard) is detected and
    masked the same way an injected dropout is — no injection involved."""
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    rows = x.shape[0] // 8
    x_bad = x.copy()
    x_bad[2 * rows:3 * rows] = np.nan
    d, i, cov = sharded_knn(q, x_bad, 5, eight_device_mesh, partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    keep = np.ones(x.shape[0], bool)
    keep[2 * rows:3 * rows] = False
    ids_map = np.nonzero(keep)[0]
    _, want = naive_knn(q, x[keep], 5)
    assert np.array_equal(np.asarray(i), ids_map[want])


def test_sharded_knn_nan_query_row_confined(shard_data, eight_device_mesh):
    """Queries are replicated, so one NaN QUERY row poisons that row on
    every shard — masking is per row: the other queries' results survive
    untouched and only the bad row degrades."""
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    d0, i0 = sharded_knn(q, x, 5, eight_device_mesh)
    q_bad = q.copy()
    q_bad[3, 0] = np.nan
    d, i, cov = sharded_knn(q_bad, x, 5, eight_device_mesh, partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(1 - 1 / q.shape[0])
    i = np.asarray(i)
    assert np.all(i[3] == -1)
    good = np.ones(q.shape[0], bool)
    good[3] = False
    assert np.array_equal(i[good], np.asarray(i0)[good])


def test_build_stream_resume_rejects_other_batch_shape(build_setup,
                                                       tmp_path):
    """Index-based batch skipping is only sound when the resumed stream
    yields the same shapes — a different make_batches must be refused,
    not silently spliced."""
    x, params, _ = build_setup
    ckdir = str(tmp_path / "bck3")
    with faultinject.inject("dead@chunk:3"):
        with pytest.raises(faultinject.InjectedDeadBackend):
            ivf_pq.build_streamed(params, _build_batches(x), _BN, _BD,
                                  trainset=x, checkpoint_dir=ckdir,
                                  checkpoint_every=1)
    with pytest.raises(ValueError, match="resume misalignment"):
        ivf_pq.build_streamed(params, _build_batches(x, bs=32), _BN, _BD,
                              trainset=x, checkpoint_dir=ckdir,
                              checkpoint_every=1, resume=True)


def test_sharded_knn_autopads_nondivisible(shard_data, eight_device_mesh):
    """Satellite: n not divisible by the mesh axis no longer raises —
    sentinel rows pad the tail shard and never surface in the top-k."""
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    x = x[:91]                               # 91 % 8 != 0
    d, i = sharded_knn(q, x, 5, eight_device_mesh)
    rd, ri = naive_knn(q, x, 5)
    assert np.array_equal(np.asarray(i), ri)
    assert np.all(np.asarray(i) >= 0)


def test_sharded_knn_autopad_with_dropout(shard_data, eight_device_mesh):
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    x = x[:91]
    rows = -(-91 // 8)                       # padded shard rows
    with faultinject.inject("shard@rank:7"):
        d, i, cov = sharded_knn(q, x, 5, eight_device_mesh, partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    keep = np.ones(91, bool)
    keep[7 * rows:] = False                  # rank 7 holds the tail + pad
    ids_map = np.nonzero(keep)[0]
    _, want = naive_knn(q, x[keep], 5)
    assert np.array_equal(np.asarray(i), ids_map[want])


@pytest.mark.parametrize("rank", [0, 4, 7])
def test_sharded_ivf_flat_dropout(rng, eight_device_mesh, rank):
    """List-sharded IVF-Flat with one dead shard: coverage drops and no
    returned id comes from the dead shard's lists."""
    from raft_tpu.comms import sharded_ivf_search

    n, m, d, k = 1024, 16, 32, 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4,
                             kmeans_trainset_fraction=1.0), x)
    sp = ivf_flat.SearchParams(n_probes=16, query_group=8,
                               local_recall_target=1.0)
    with faultinject.inject(f"shard@rank:{rank}"):
        dist, idx, cov = sharded_ivf_search(sp, index, q, k,
                                            eight_device_mesh,
                                            partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    local = 16 // 8
    dead_ids = set(
        np.asarray(index.indices)[rank * local:(rank + 1) * local].ravel()
    ) - {-1}
    got = set(np.asarray(idx).ravel()) - {-1}
    assert not (got & dead_ids)
    assert np.all(np.isfinite(np.asarray(dist)[np.asarray(idx) >= 0]))


@pytest.mark.parametrize("rank", [1, 6])
def test_sharded_ivf_pq_dropout(rng, eight_device_mesh, rank):
    from raft_tpu.comms import sharded_ivf_pq_search

    n, m, d, k = 1024, 16, 32, 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, pq_dim=16, pq_bits=8,
                           kmeans_n_iters=4,
                           kmeans_trainset_fraction=1.0), x)
    sp = ivf_pq.SearchParams(n_probes=16, query_group=8,
                             local_recall_target=1.0)
    with faultinject.inject(f"shard@rank:{rank}"):
        dist, idx, cov = sharded_ivf_pq_search(sp, index, q, k,
                                               eight_device_mesh,
                                               partial_ok=True)
    assert float(np.asarray(cov)) == pytest.approx(7 / 8)
    local = 16 // 8
    dead_ids = set(
        np.asarray(index.indices)[rank * local:(rank + 1) * local].ravel()
    ) - {-1}
    got = set(np.asarray(idx).ravel()) - {-1}
    assert not (got & dead_ids)


def test_sharded_partial_ok_full_coverage(shard_data, eight_device_mesh):
    """partial_ok with NO fault returns coverage 1.0 and the same answer
    as the plain call."""
    from raft_tpu.comms import sharded_knn

    x, q = shard_data
    d0, i0 = sharded_knn(q, x, 5, eight_device_mesh)
    d1, i1, cov = sharded_knn(q, x, 5, eight_device_mesh, partial_ok=True)
    assert float(np.asarray(cov)) == 1.0
    assert np.array_equal(np.asarray(i0), np.asarray(i1))


# ---------------------------------------------------------------------------
# batch iterator hooks
# ---------------------------------------------------------------------------


def test_batch_iterator_live_shrink_and_start_row():
    from raft_tpu.utils.batch import BatchLoadIterator

    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    it = BatchLoadIterator(x, 8)
    seen = []
    for off, batch in it:
        seen.append((off, batch.shape[0]))
        if off == 0:
            it.set_batch_rows(4)
    # the one-slot prefetch means the shrink lands one batch later —
    # batch (8, 8) was already staged when (0, 8) was consumed
    assert seen == [(0, 8), (8, 8), (16, 4)]

    it2 = BatchLoadIterator(x, 8, start_row=8)
    assert [off for off, _ in it2] == [8, 16]
    assert len(it2) == 2


# ---------------------------------------------------------------------------
# graft-race regression (ISSUE 7): one-shot spec consumption discipline
# ---------------------------------------------------------------------------


def test_faultinject_one_shot_exact_under_concurrency():
    """A `*K` spec fires exactly K times across racing consumers: plan
    resolution and the `remaining` decrement share ONE critical
    section (the old fetch-then-relock consumed off a detached list)."""
    from raft_tpu.resilience import faultinject

    faultinject.install("slow@proc:0*5")
    try:
        hits = []
        barrier = threading.Barrier(8)

        def consume():
            barrier.wait()
            for _ in range(4):
                if faultinject.proc_action(0) == "slow":
                    hits.append(1)

        ts = [threading.Thread(target=consume, daemon=True)
              for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(hits) == 5, len(hits)
    finally:
        faultinject.clear()


def test_faultinject_clear_wins_over_stale_plan():
    """After clear(), a consumer must see the LIVE (empty) plan — not a
    list it fetched before the swap."""
    from raft_tpu.resilience import faultinject

    faultinject.install("dead@proc:0*1")
    faultinject.clear()
    assert faultinject.proc_action(0) is None
    faultinject.install("drop@rpc:search*1")
    faultinject.install(None)
    assert not faultinject.rpc_dropped("search")
