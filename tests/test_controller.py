"""graft-helm control plane (ISSUE 18; markers ``multihost`` +
``threadsan``).

Covers: the WorkerHealth probational-readmission hysteresis (a
probe-pass-then-fail worker re-opens WITHOUT a refilled failure
budget), p2c replica load-balancing spreading one shard's reads over
ALL its owners, dynamic membership (admit/drain) with bitwise answer
continuity and zero mixed-generation merges, the repair loop
(respawn-then-evict against the rebalance budget, replication factor
restored on the survivors), the autoscaler's grow-then-shrink with
cooldown/sustain hysteresis and saturated-stage hold reasons, and the
thrash NEGATIVE test: a ``flap@proc`` worker is respawned, never
evicted, and never causes a scale action.

All tests run the in-process :class:`LocalGroup` transport under
sanitized locks; the spawn-worker chaos acceptance lives in
tests/test_fabric.py and the shipped FABRIC artifact.
"""

import time

import numpy as np
import pytest

from raft_tpu import serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.resilience import faultinject
from raft_tpu.serve import fabric as fabmod
from raft_tpu.serve.fabric import CLOSED, HALF_OPEN, OPEN, WorkerHealth

pytestmark = [pytest.mark.multihost, pytest.mark.threadsan]


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    faultinject.clear()
    tuning.reload()
    yield
    faultinject.clear()
    tuning.reload()


def _params(**kw):
    base = dict(
        n_workers=3, replication=2, rpc_deadline_s=3.0,
        rpc_retries=2, retry_backoff_s=0.01, hedge_after_ms=25.0,
        halfopen_after_s=0.02, probe_timeout_s=10.0,
        swap_deadline_s=30.0, slow_ms=150.0, auto_probe=False,
        fail_threshold=2,
    )
    base.update(kw)
    return serve.FabricParams(**base)


def _helm_params(**kw):
    base = dict(
        interval_s=0.02, rebalance_budget_ms=150.0, restart_budget=0,
        min_workers=2, max_workers=5, sustain_ticks=2, cooldown_s=0.05,
        retire_timeout_s=5.0,
    )
    base.update(kw)
    return serve.HelmParams(**base)


def _data(n=96, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, dim)).astype(np.float32),
            rng.standard_normal((4, dim)).astype(np.float32))


def _spin(fab, helm, pred, timeout_s=10.0, probe=True):
    """Tick controller + prober until ``pred()`` or timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        helm.step()
        if probe:
            fab.probe_now()
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------------------
# WorkerHealth flapping hysteresis (satellite: pinned breaker contract)
# ---------------------------------------------------------------------------


def test_health_probational_readmission_keeps_budget_spent():
    hl = WorkerHealth(0, fail_threshold=3, halfopen_after_s=0.0,
                      probation_successes=3)
    for _ in range(3):
        hl.record_failure("transient")
    assert hl.state == OPEN
    hl.to_half_open()
    assert hl.state == HALF_OPEN
    # probe passes: closed again — but the failure budget stays spent
    hl.record_success()
    assert hl.state == CLOSED
    # ONE failure re-opens (pre-ISSUE-18 it took fail_threshold fresh
    # ones — the flapping worker served 2 more failing requests per
    # flap cycle)
    hl.record_failure("transient")
    assert hl.state == OPEN


def test_health_budget_refills_after_probation():
    hl = WorkerHealth(0, fail_threshold=3, halfopen_after_s=0.0,
                      probation_successes=3)
    for _ in range(3):
        hl.record_failure("transient")
    hl.to_half_open()
    hl.record_success()
    assert hl.state == CLOSED
    # probation: 2 more consecutive successes refill the budget
    hl.record_success()
    hl.record_success()
    hl.record_failure("transient")
    assert hl.state == CLOSED          # budget refilled: 1 of 3 spent
    hl.record_failure("transient")
    hl.record_failure("transient")
    assert hl.state == OPEN


def test_health_open_episode_survives_failed_halfopen_probe():
    hl = WorkerHealth(0, fail_threshold=1, halfopen_after_s=0.0,
                      probation_successes=2)
    hl.record_failure("dead_backend")
    assert hl.state == OPEN
    first_since = hl.open_since
    assert first_since > 0.0
    # failed half-open probe: back to OPEN, but the EPISODE clock keeps
    # its original start — a dead worker's time-to-evict is measured
    # from its first trip, not its latest failed probe
    hl.to_half_open()
    hl.record_failure("transient")
    assert hl.state == OPEN
    assert hl.open_since == first_since
    # readmission ends the episode
    hl.to_half_open()
    hl.record_success()
    assert hl.state == CLOSED and hl.open_since == 0.0


# ---------------------------------------------------------------------------
# p2c replica load balancing
# ---------------------------------------------------------------------------


def test_p2c_spreads_one_shards_reads_over_all_owners():
    ds, q = _data()
    # ONE shard, TWO owners: primary-first routing would pin every read
    # on worker 0 while worker 1 idles as a failover spare
    p = _params(n_workers=2, replication=2, n_shards=1)
    with serve.Fabric(ds, params=p, group="local") as fab:
        for _ in range(24):
            d, i, cov = fab.search(q, 5)
            assert (cov == 1.0).all()
        ewma = fab.load_snapshot()["ewma_ms"]
        # both owners measured => both actually served reads
        assert set(ewma) == {0, 1}, ewma


def test_primary_baseline_keeps_declared_order():
    ds, q = _data()
    p = _params(n_workers=2, replication=2, n_shards=1,
                balance="primary")
    with serve.Fabric(ds, params=p, group="local") as fab:
        for _ in range(24):
            fab.search(q, 5)
        ewma = fab.load_snapshot()["ewma_ms"]
        # primary-first: worker 1 never serves a healthy-path read
        assert 0 in ewma and 1 not in ewma, ewma


def test_p2c_answers_stay_bitwise_vs_oracle():
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        bounds_shards = fab.n_shards
        for _ in range(8):
            d, i, cov = fab.search(q, 5)
            assert (cov == 1.0).all()
            # replicas hold identical shard slices and run the same
            # search path — routing choice can never change the answer
            od, oi, _ = _oracle_local(ds, q, 5, bounds_shards)
            np.testing.assert_array_equal(i, oi)
            np.testing.assert_array_equal(d, od)
        assert fab.stats()["counters"].get("mixed_gen", 0) == 0


def _oracle_local(dataset, q, k, n_shards):
    from raft_tpu.comms import procgroup
    bounds = fabmod.shard_bounds(dataset.shape[0], n_shards)
    results = {}
    for s in range(n_shards):
        entry = procgroup.build_shard_entry(
            dataset[bounds[s]:bounds[s + 1]], bounds[s], "brute_force")
        d, i = procgroup.search_shard_entry(entry, q, k)
        results[s] = (0, d, i)
    return fabmod.merge_shard_results(n_shards, results, q.shape[0], k)


# ---------------------------------------------------------------------------
# dynamic membership on the fabric surface
# ---------------------------------------------------------------------------


def test_add_and_retire_worker_bitwise_continuity():
    ds, q = _data()
    with serve.Fabric(ds, params=_params(), group="local") as fab:
        od, oi, _ = _oracle_local(ds, q, 5, fab.n_shards)
        rank = fab.add_worker()
        assert rank == 3 and fab.member_ranks() == [0, 1, 2, 3]
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        np.testing.assert_array_equal(i, oi)
        fab.retire_worker(0, timeout_s=5.0)
        assert fab.active_ranks() == [1, 2, 3]
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        # shard count never changed; every shard kept `replication`
        # distinct owners drawn from the survivors
        owners = fab.registry.get(fab.name).handle.owners
        assert len(owners) == fab.n_shards
        for ranks in owners.values():
            assert len(set(ranks)) == 2
            assert all(r in (1, 2, 3) for r in ranks)
        assert fab.stats()["counters"].get("mixed_gen", 0) == 0
        # a retired rank is permanently out
        with pytest.raises(ValueError):
            fab.restart_worker(0)


def test_retire_below_one_admissible_worker_raises():
    ds, _q = _data()
    p = _params(n_workers=2, replication=2)
    with serve.Fabric(ds, params=p, group="local") as fab:
        fab.retire_worker(0, timeout_s=5.0)
        with pytest.raises(fabmod.FabricSwapError):
            fab.retire_worker(1, timeout_s=5.0)
        # the failed retire rolled back: rank 1 still serves
        d, i, cov = fab.search(_q, 5)
        assert (cov == 1.0).all()


# ---------------------------------------------------------------------------
# helm repair loop: respawn, then evict against the rebalance budget
# ---------------------------------------------------------------------------


def test_helm_evicts_dead_worker_and_restores_replication():
    ds, q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local",
                       fault_spec="dead@proc:2")
    helm = serve.HelmController(fab, params=_helm_params())
    try:
        fab.search(q, 5)                      # trips dead@proc:2
        assert fab.stats()["health"][2] == "open"
        assert _spin(fab, helm,
                     lambda: 2 in helm.stats()["evicted"])
        # survivors hold full coverage AND the full replication factor
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        owners = fab.registry.get(fab.name).handle.owners
        for ranks in owners.values():
            assert len(set(ranks)) == 2 and 2 not in ranks
        od, oi, _ = _oracle_local(ds, q, 5, fab.n_shards)
        np.testing.assert_array_equal(i, oi)
        assert fab.stats()["counters"].get("mixed_gen", 0) == 0
    finally:
        helm.stop()
        fab.close()


def test_helm_respawns_before_spending_rebalance_budget():
    ds, q = _data()
    # ambient LocalGroup plan: dead@proc:2 fires ONCE — the respawned
    # worker is genuinely healthy, so repair ends at readmission
    fab = serve.Fabric(ds, params=_params(), group="local",
                       fault_spec="dead@proc:2")
    helm = serve.HelmController(
        fab, params=_helm_params(restart_budget=2))
    try:
        fab.search(q, 5)
        assert fab.stats()["health"][2] == "open"
        assert _spin(fab, helm,
                     lambda: fab.stats()["health"].get(2) == "closed")
        st = helm.stats()
        assert st["restarts"].get(2, 0) == 1
        assert st["evicted"] == []
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
    finally:
        helm.stop()
        fab.close()


def test_helm_thrash_negative_under_flap():
    """The ISSUE 18 anti-thrash contract: a FLAPPING worker (dies,
    respawns, dies again — ``flap@proc:1*2``) is repaired in place and
    never triggers an eviction, a scale action, or a generation churn:
    every readmission clears the open-episode clock, the degraded-fleet
    gate parks the autoscaler, and membership ends exactly where it
    started."""
    ds, q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local",
                       fault_spec="flap@proc:1*2")
    helm = serve.HelmController(
        fab, params=_helm_params(restart_budget=5,
                                 rebalance_budget_ms=2000.0))
    gen0 = fab.generation()
    try:
        deadline = time.monotonic() + 12.0
        flaps_done = 0
        while time.monotonic() < deadline:
            try:
                d, i, cov = fab.search(q, 5)
                assert (cov == 1.0).all()
            except Exception:
                pass                      # a batch mid-death may drop
            helm.step()
            fab.probe_now()
            st = helm.stats()
            if (st["restarts"].get(1, 0) >= 2
                    and fab.stats()["health"].get(1) == "closed"):
                flaps_done = st["restarts"][1]
                break
            time.sleep(0.01)
        assert flaps_done >= 2, helm.stats()
        st = helm.stats()
        c = fab.stats()["counters"]
        assert st["evicted"] == []                      # no eviction
        assert c.get("adds", 0) == 0                    # no scale-up
        assert c.get("retires", 0) == 0                 # no drain
        assert c.get("rebalances", 0) == 0              # no gen churn
        assert fab.generation() == gen0
        assert fab.active_ranks() == [0, 1, 2]
        # steady state: everyone closed, answers exact
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle_local(ds, q, 5, fab.n_shards)
        np.testing.assert_array_equal(i, oi)
    finally:
        helm.stop()
        fab.close()


# ---------------------------------------------------------------------------
# helm autoscaler: grow-then-shrink with hysteresis
# ---------------------------------------------------------------------------


def _force_inflight(fab, value):
    with fab._load_lock:
        for r in fab.active_ranks():
            fab._inflight[r] = value


def test_helm_scales_up_then_down_with_hysteresis():
    ds, q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local")
    helm = serve.HelmController(
        fab, params=_helm_params(sustain_ticks=3, cooldown_s=0.05,
                                 scale_up_inflight=3.0,
                                 scale_down_inflight=0.25))
    try:
        # saturate the load signal: sustain gate holds the first two
        # ticks, the third admits a worker
        _force_inflight(fab, 10)
        assert helm.step()["actions"] == []
        assert helm.step()["actions"] == []
        rep = helm.step()
        assert rep["actions"] == [("scale_up", 3)]
        assert fab.active_ranks() == [0, 1, 2, 3]
        # still hot, but the cooldown parks further growth
        _force_inflight(fab, 10)
        for _ in range(3):
            rep = helm.step()
        assert rep["held"] == "cooldown" and rep["workers"] == 4
        time.sleep(0.06)
        # load drains: sustained cold signal drains the NEWEST rank
        _force_inflight(fab, 0)
        for _ in range(3):
            rep = helm.step()
        assert rep["actions"] == [("scale_down", 3)]
        assert fab.active_ranks() == [0, 1, 2]
        # the fleet never goes below max(min_workers, replication)
        time.sleep(0.06)
        rep = None
        for _ in range(3):
            rep = helm.step()
        assert rep["actions"] == [("scale_down", 2)]
        time.sleep(0.06)
        for _ in range(3):
            rep = helm.step()
        assert rep["held"] == "min_workers"
        assert fab.active_ranks() == [0, 1]
        # answers remain exact through every membership change
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle_local(ds, q, 5, fab.n_shards)
        np.testing.assert_array_equal(i, oi)
        assert fab.stats()["counters"].get("mixed_gen", 0) == 0
    finally:
        helm.stop()
        fab.close()


def test_helm_holds_when_router_bound(monkeypatch):
    ds, _q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local")
    helm = serve.HelmController(
        fab, params=_helm_params(sustain_ticks=1, cooldown_s=0.0))
    try:
        monkeypatch.setattr(helm, "_worker_bound", lambda: False)
        _force_inflight(fab, 10)
        rep = helm.step()
        # merge-dominated waterfalls: another worker would not move the
        # p99 — hold with the reason instead of spending a machine
        assert rep["held"] == "router_bound" and rep["actions"] == []
        assert fab.active_ranks() == [0, 1, 2]
    finally:
        helm.stop()
        fab.close()


def test_helm_max_workers_ceiling():
    ds, _q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local")
    helm = serve.HelmController(
        fab, params=_helm_params(sustain_ticks=1, cooldown_s=0.0,
                                 max_workers=3))
    try:
        _force_inflight(fab, 10)
        rep = helm.step()
        assert rep["held"] == "max_workers" and rep["actions"] == []
    finally:
        helm.stop()
        fab.close()


# ---------------------------------------------------------------------------
# real multiprocessing: fault-plan inheritance across respawns
# ---------------------------------------------------------------------------


def test_helm_multiprocess_flap_heals_dead_evicts():
    """ProcGroup-only semantics (each child owns a COPY of the plan, so
    cross-incarnation budgets are charged parent-side via
    ``respawned_spec``): a ``flap@proc`` worker dies, is respawned with
    a decremented flap budget, dies again, and finally holds — while a
    ``dead@proc`` worker stays dead through every inherited respawn,
    exhausts the restart budget, and is evicted with its shards
    re-replicated onto the survivors."""
    ds, q = _data(n=120)
    p = _params(rpc_deadline_s=5.0, probe_timeout_s=10.0,
                swap_deadline_s=60.0, halfopen_after_s=0.05)
    fab = serve.Fabric(ds, params=p, group="proc",
                       fault_spec="flap@proc:1*2,dead@proc:2")
    helm = serve.HelmController(
        fab, params=_helm_params(restart_budget=3,
                                 rebalance_budget_ms=500.0,
                                 retire_timeout_s=20.0))
    try:
        deadline = time.monotonic() + 120.0
        rng = np.random.default_rng(9)

        def settled():
            st = helm.stats()
            h = fab.stats()["health"]
            return (2 in st["evicted"]
                    and st["restarts"].get(1, 0) >= 2
                    and h.get(1) == "closed")

        while time.monotonic() < deadline and not settled():
            try:
                fab.search(rng.standard_normal(
                    (1, 8)).astype(np.float32), 4)
            except Exception:
                pass                       # mid-death batches may drop
            helm.step()
            fab.probe_now()
            time.sleep(0.05)
        assert settled(), (helm.stats(), fab.stats())
        # worker 1 held after its flap budget spent; worker 2 is out
        # and every shard kept `replication` owners on the survivors
        owners = fab.registry.get(fab.name).handle.owners
        for ranks in owners.values():
            assert len(set(ranks)) == 2 and 2 not in ranks
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
        od, oi, _ = _oracle_local(ds, q, 5, fab.n_shards)
        np.testing.assert_array_equal(i, oi)
        np.testing.assert_array_equal(d, od)
        assert fab.stats()["counters"].get("mixed_gen", 0) == 0
    finally:
        helm.stop()
        fab.close()


def test_helm_operator_overrides_spanned():
    ds, q = _data()
    fab = serve.Fabric(ds, params=_params(), group="local")
    helm = serve.HelmController(fab, params=_helm_params())
    try:
        rank = helm.scale_up()
        assert rank == 3 and len(fab.active_ranks()) == 4
        gone = helm.scale_down()
        assert gone == 3 and len(fab.active_ranks()) == 3
        gen = helm.rebalance(reason="drill")
        assert gen == fab.generation()
        d, i, cov = fab.search(q, 5)
        assert (cov == 1.0).all()
    finally:
        helm.stop()
        fab.close()
