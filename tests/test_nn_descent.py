"""NN-descent tests — reference pattern (cpp/test/neighbors/ann_nn_descent.cuh):
all-KNN-graph recall vs exact oracle."""

import numpy as np
import pytest

from raft_tpu.neighbors import cagra, nn_descent
from tests.oracles import eval_recall, naive_knn


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(21)
    centers = rng.uniform(-5, 5, (16, 24)).astype(np.float32)
    x = (centers[rng.integers(0, 16, 6000)]
         + 0.8 * rng.standard_normal((6000, 24))).astype(np.float32)
    return x


def test_all_knn_graph_recall(dataset):
    x = dataset
    k = 32
    params = nn_descent.IndexParams(graph_degree=k, max_iterations=20)
    index = nn_descent.build(params, x)
    assert index.graph.shape == (x.shape[0], k)
    g = np.asarray(index.graph)
    # oracle on a subset: true neighbors 1..k (self excluded)
    sub = 300
    _, want = naive_knn(x[:sub], x, k + 1)
    rec = np.mean(
        [len(set(g[i]) & set(want[i][1:k + 1])) / k for i in range(sub)]
    )
    assert rec > 0.9, rec
    # distances are true metric values for the returned ids
    d = np.asarray(index.distances)
    for i in range(5):
        true = ((x[i] - x[g[i, 0]]) ** 2).sum()
        np.testing.assert_allclose(d[i, 0], true, rtol=1e-3, atol=1e-3)


def test_no_self_edges_no_dups(dataset):
    x = dataset
    params = nn_descent.IndexParams(graph_degree=16, max_iterations=8)
    index = nn_descent.build(params, x)
    g = np.asarray(index.graph)
    assert not (g == np.arange(x.shape[0])[:, None]).any()
    for i in range(0, 200, 7):
        row = g[i][g[i] >= 0]
        assert len(set(row)) == len(row)


def test_recall_non_pow2_n():
    """Non-pow2 row counts: the blocked join's tail block and the
    reverse-graph pack must cover every row (recall-vs-brute oracle)."""
    rng = np.random.default_rng(5)
    x = rng.standard_normal((777, 16)).astype(np.float32)
    k = 16
    index = nn_descent.build(
        nn_descent.IndexParams(graph_degree=k, max_iterations=12), x)
    g = np.asarray(index.graph)
    assert g.shape == (777, k)
    assert g.max() < 777 and not (
        g == np.arange(777)[:, None]).any()
    _, want = naive_knn(x, x, k + 1)
    rec = np.mean(
        [len(set(g[i]) & set(want[i][1:k + 1])) / k for i in range(777)])
    assert rec > 0.9, rec


def test_tiny_n_below_intermediate_degree():
    """n < intermediate_graph_degree: K clamps to n-1 and the build
    still returns a full, valid, near-exact graph."""
    rng = np.random.default_rng(6)
    n, k = 40, 16
    x = rng.standard_normal((n, 8)).astype(np.float32)
    index = nn_descent.build(
        nn_descent.IndexParams(graph_degree=k,
                               intermediate_graph_degree=64,
                               max_iterations=10), x)
    g = np.asarray(index.graph)
    assert g.shape == (n, k)
    assert g.max() < n and g.min() >= 0
    assert not (g == np.arange(n)[:, None]).any()
    _, want = naive_knn(x, x, k + 1)
    rec = np.mean(
        [len(set(g[i]) & set(want[i][1:k + 1])) / k for i in range(n)])
    assert rec > 0.95, rec


def test_inner_product_metric():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((2000, 24)).astype(np.float32)
    k = 16
    index = nn_descent.build(
        nn_descent.IndexParams(graph_degree=k, max_iterations=12,
                               metric="inner_product"), x)
    g = np.asarray(index.graph)
    _, want = naive_knn(x[:200], x, k + 1, metric="inner_product")
    rec = np.mean(
        [len(set(g[i]) & set(want[i][1:k + 1])) / k for i in range(200)])
    assert rec > 0.85, rec
    # distances are true (sign-restored) inner products of returned ids
    d = np.asarray(index.distances)
    for i in range(5):
        np.testing.assert_allclose(
            d[i, 0], float(x[i] @ x[g[i, 0]]), rtol=1e-3, atol=1e-3)


def test_blocked_matches_unblocked_bitwise():
    """The blocked iteration is a pure memory-shape choice: covering
    the rows in tiles (OOM-ladder path) must reproduce the single-block
    dispatch bitwise — ids AND distances."""
    rng = np.random.default_rng(12)
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    mk = lambda rows: nn_descent.IndexParams(
        graph_degree=16, max_iterations=5, block_rows=rows)
    blocked = nn_descent.build(mk(256), x)        # 6 tiles, ragged tail
    whole = nn_descent.build(mk(1 << 20), x)      # one dispatch
    np.testing.assert_array_equal(np.asarray(blocked.graph),
                                  np.asarray(whole.graph))
    np.testing.assert_array_equal(np.asarray(blocked.distances),
                                  np.asarray(whole.distances))


def test_oom_ladder_covers_the_join_bitwise():
    """A RESOURCE_EXHAUSTED mid-join halves the block (OOM ladder,
    stage nn_descent.join) instead of killing the build, and the
    shrunken cover reproduces the unfaulted graph bitwise (the join is
    row-independent). The survivor size lands in the graph_join_rows
    runtime budget."""
    from raft_tpu import tuning
    from raft_tpu.resilience import faultinject

    rng = np.random.default_rng(15)
    x = rng.standard_normal((1000, 16)).astype(np.float32)
    params = nn_descent.IndexParams(graph_degree=16, max_iterations=3,
                                    block_rows=400)
    clean = nn_descent.build(params, x)
    try:
        with faultinject.inject("oom@stage:nn_descent.join"):
            faulted = nn_descent.build(params, x)
        assert tuning.runtime_budget("graph_join_rows") == 200
    finally:
        tuning.reload()
    np.testing.assert_array_equal(np.asarray(faulted.graph),
                                  np.asarray(clean.graph))


def test_convergence_window_matches_truncated_build():
    """The device-side convergence window syncs once per check_every
    iterations: with a threshold every iteration clears, the build must
    stop at the first window — bitwise the same graph as a build capped
    at check_every iterations (same key schedule)."""
    rng = np.random.default_rng(13)
    x = rng.standard_normal((1200, 16)).astype(np.float32)
    early = nn_descent.build(
        nn_descent.IndexParams(graph_degree=16, max_iterations=20,
                               termination_threshold=10.0,
                               check_every=3), x)
    capped = nn_descent.build(
        nn_descent.IndexParams(graph_degree=16, max_iterations=3,
                               termination_threshold=0.0), x)
    np.testing.assert_array_equal(np.asarray(early.graph),
                                  np.asarray(capped.graph))


def test_join_impl_pallas_interpret_agrees():
    """The fused local-join kernel serving a whole build (interpret
    mode) stays in lockstep with the XLA fallback — per-step the two
    are bitwise (tests/test_graph_join.py); across iterations ulp-scale
    scoring ties may diverge a handful of picks, so judge agreement and
    recall, not equality."""
    rng = np.random.default_rng(14)
    centers = rng.uniform(-5, 5, (8, 16)).astype(np.float32)
    x = (centers[rng.integers(0, 8, 900)]
         + 0.6 * rng.standard_normal((900, 16))).astype(np.float32)
    mk = lambda impl: nn_descent.IndexParams(
        graph_degree=16, max_iterations=8, join_impl=impl)
    gp = nn_descent.build(mk("pallas_interpret"), x)
    gx = nn_descent.build(mk("xla"), x)
    agree = (np.asarray(gp.graph) == np.asarray(gx.graph)).mean()
    assert agree > 0.98, agree
    _, want = naive_knn(x[:150], x, 17)
    for idx in (gp, gx):
        g = np.asarray(idx.graph)
        rec = np.mean([
            len(set(g[i]) & set(want[i][1:17])) / 16 for i in range(150)])
        assert rec > 0.9, rec


def test_cagra_with_nn_descent_builder(dataset):
    x = dataset
    params = cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24,
        graph_build_algo=cagra.build_algo.NN_DESCENT,
    )
    index = cagra.build(params, x)
    rng = np.random.default_rng(3)
    q = x[rng.integers(0, x.shape[0], 100)] + 0.05 * rng.standard_normal(
        (100, x.shape[1])
    ).astype(np.float32)
    sp = cagra.SearchParams(itopk_size=64, search_width=2)
    _, idx = cagra.search(sp, index, q.astype(np.float32), 10)
    _, want = naive_knn(q.astype(np.float32), x, 10)
    assert eval_recall(np.asarray(idx), want) > 0.9
