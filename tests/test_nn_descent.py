"""NN-descent tests — reference pattern (cpp/test/neighbors/ann_nn_descent.cuh):
all-KNN-graph recall vs exact oracle."""

import numpy as np
import pytest

from raft_tpu.neighbors import cagra, nn_descent
from tests.oracles import eval_recall, naive_knn


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(21)
    centers = rng.uniform(-5, 5, (16, 24)).astype(np.float32)
    x = (centers[rng.integers(0, 16, 6000)]
         + 0.8 * rng.standard_normal((6000, 24))).astype(np.float32)
    return x


def test_all_knn_graph_recall(dataset):
    x = dataset
    k = 32
    params = nn_descent.IndexParams(graph_degree=k, max_iterations=20)
    index = nn_descent.build(params, x)
    assert index.graph.shape == (x.shape[0], k)
    g = np.asarray(index.graph)
    # oracle on a subset: true neighbors 1..k (self excluded)
    sub = 300
    _, want = naive_knn(x[:sub], x, k + 1)
    rec = np.mean(
        [len(set(g[i]) & set(want[i][1:k + 1])) / k for i in range(sub)]
    )
    assert rec > 0.9, rec
    # distances are true metric values for the returned ids
    d = np.asarray(index.distances)
    for i in range(5):
        true = ((x[i] - x[g[i, 0]]) ** 2).sum()
        np.testing.assert_allclose(d[i, 0], true, rtol=1e-3, atol=1e-3)


def test_no_self_edges_no_dups(dataset):
    x = dataset
    params = nn_descent.IndexParams(graph_degree=16, max_iterations=8)
    index = nn_descent.build(params, x)
    g = np.asarray(index.graph)
    assert not (g == np.arange(x.shape[0])[:, None]).any()
    for i in range(0, 200, 7):
        row = g[i][g[i] >= 0]
        assert len(set(row)) == len(row)


def test_cagra_with_nn_descent_builder(dataset):
    x = dataset
    params = cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24,
        graph_build_algo=cagra.build_algo.NN_DESCENT,
    )
    index = cagra.build(params, x)
    rng = np.random.default_rng(3)
    q = x[rng.integers(0, x.shape[0], 100)] + 0.05 * rng.standard_normal(
        (100, x.shape[1])
    ).astype(np.float32)
    sp = cagra.SearchParams(itopk_size=64, search_width=2)
    _, idx = cagra.search(sp, index, q.astype(np.float32), 10)
    _, want = naive_knn(q.astype(np.float32), x, 10)
    assert eval_recall(np.asarray(idx), want) > 0.9
