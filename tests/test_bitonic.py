"""Bitonic network tests (reference util/bitonic_sort.cuh analog) plus
the CAGRA search-path equivalences that ride on it."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.matrix.bitonic import merge_sorted, sort_by_key


@pytest.mark.parametrize("L", [2, 8, 128, 256])
def test_sort_matches_numpy(L):
    rng = np.random.default_rng(3)
    k = rng.standard_normal((9, L)).astype(np.float32)
    p = rng.integers(0, 10_000, (9, L)).astype(np.int32)
    sk, (sp,) = sort_by_key(jnp.asarray(k), jnp.asarray(p))
    assert np.allclose(np.asarray(sk), np.sort(k, axis=1))
    # key/payload pairing preserved (as multisets; ties may reorder)
    for r in range(9):
        a = sorted(zip(k[r].tolist(), p[r].tolist()))
        b = sorted(zip(np.asarray(sk)[r].tolist(), np.asarray(sp)[r].tolist()))
        assert a == b


def test_sort_descending_and_multi_payload():
    rng = np.random.default_rng(4)
    k = rng.standard_normal((5, 64)).astype(np.float32)
    p1 = rng.integers(0, 99, (5, 64)).astype(np.int32)
    p2 = rng.random((5, 64)) > 0.5
    sk, (sp1, sp2) = sort_by_key(jnp.asarray(k), jnp.asarray(p1),
                                 jnp.asarray(p2), descending=True)
    assert np.allclose(np.asarray(sk), -np.sort(-k, axis=1))
    assert sp1.dtype == jnp.int32 and sp2.dtype == jnp.bool_


def test_sort_with_inf_padding():
    k = np.array([[3.0, np.inf, 1.0, np.inf]], np.float32)
    p = np.array([[30, -1, 10, -1]], np.int32)
    sk, (sp,) = sort_by_key(jnp.asarray(k), jnp.asarray(p))
    assert np.asarray(sp)[0, :2].tolist() == [10, 30]
    assert np.isinf(np.asarray(sk)[0, 2:]).all()


def test_merge_sorted_halves():
    rng = np.random.default_rng(5)
    h = np.sort(rng.standard_normal((6, 2, 64)).astype(np.float32),
                axis=2).reshape(6, 128)
    p = rng.integers(0, 999, (6, 128)).astype(np.int32)
    mk, (mp,) = merge_sorted(jnp.asarray(h), jnp.asarray(p))
    assert np.allclose(np.asarray(mk), np.sort(h, axis=1))


def test_non_pow2_raises():
    with pytest.raises(ValueError):
        sort_by_key(jnp.zeros((2, 96)))


def test_cagra_inline_vs_scattered_paths():
    """Both beam-search paths must agree to high recall on the same
    index (inline traversal is int8-approximate but exactly rescored)."""
    from raft_tpu.neighbors import cagra
    from tests.oracles import eval_recall, naive_knn

    rng = np.random.default_rng(12)
    centers = rng.uniform(-5, 5, (16, 32)).astype(np.float32)
    x = (centers[rng.integers(0, 16, 4000)]
         + 0.7 * rng.standard_normal((4000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 16, 100)]
         + 0.7 * rng.standard_normal((100, 32))).astype(np.float32)
    idx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16), x)
    assert idx.nbr_pack is not None and idx.flat_codes is not None
    scat = cagra.Index(dataset=idx.dataset, graph=idx.graph,
                       metric=idx.metric, data_norms=idx.data_norms)
    k = 10
    # force the packed/Pallas path for the inline index (on CPU "auto"
    # would resolve both searches to the same scattered implementation)
    d_i, i_i = cagra.search(
        cagra.SearchParams(itopk_size=64, search_width=4,
                           scan_impl="pallas_interpret"), idx, q, k)
    d_s, i_s = cagra.search(
        cagra.SearchParams(itopk_size=64, search_width=4), scat, q, k)
    _, want = naive_knn(q, x, k)
    assert eval_recall(np.asarray(i_i), want) > 0.9
    assert eval_recall(np.asarray(i_s), want) > 0.9
    # no duplicate ids within a result row (windowed-dedup invariant)
    for res in (np.asarray(i_i), np.asarray(i_s)):
        for r in range(res.shape[0]):
            row = res[r][res[r] >= 0]
            assert len(set(row.tolist())) == len(row)
    # inline distances are exact (final rescore) — same values both paths
    both = (np.asarray(i_i) == np.asarray(i_s))
    assert np.allclose(np.asarray(d_i)[both], np.asarray(d_s)[both],
                       rtol=1e-4, atol=1e-4)


def test_cagra_forced_f32_uses_scattered_path():
    """compute_dtype='f32' must force exact scattered scoring even on an
    index that carries the inline layout."""
    from raft_tpu.neighbors import cagra

    rng = np.random.default_rng(13)
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((50, 16)).astype(np.float32)
    idx = cagra.build(cagra.IndexParams(
        intermediate_graph_degree=24, graph_degree=12), x)
    scat = cagra.Index(dataset=idx.dataset, graph=idx.graph,
                       metric=idx.metric, data_norms=idx.data_norms)
    sp32 = cagra.SearchParams(itopk_size=32, search_width=2,
                              compute_dtype="f32")
    d_f, i_f = cagra.search(sp32, idx, q, 5)
    d_s, i_s = cagra.search(sp32, scat, q, 5)
    np.testing.assert_array_equal(np.asarray(i_f), np.asarray(i_s))
    np.testing.assert_allclose(np.asarray(d_f), np.asarray(d_s))
