"""Real multi-process distributed test — the raft-dask-analog bootstrap
(raft_tpu.bootstrap.init_multihost) exercised with TWO OS processes over jax.distributed
(gloo on CPU), running a psum and a sharded KNN across the process mesh.

This is the multi-host path the reference covers with its NCCL/MPI comms
tests (cpp/test/core/device_resources_manager.cu + raft-dask test_comms);
single-process CPU-mesh tests elsewhere cover the collectives themselves.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from raft_tpu.bootstrap import init_multihost
    init_multihost(coordinator_address=f"127.0.0.1:{port}",
                   num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    import numpy as np
    from raft_tpu.comms.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices())
    assert len(devs) == nproc, f"expected {nproc} global devices, got {devs}"
    mesh = Mesh(devs, ("shard",))

    # collective sanity: psum across hosts
    def f(x):
        return jax.lax.psum(x, "shard")

    y = jax.jit(shard_map(f, mesh=mesh, in_specs=P("shard"), out_specs=P()))(
        jnp.ones((nproc,), jnp.float32)
    )
    assert float(y[0]) == nproc

    # sharded brute-force KNN over the cross-process mesh
    from raft_tpu.comms import sharded_knn

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64 * nproc, 16)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    d, i = sharded_knn(q, x, 4, mesh)
    # oracle on every host (same data everywhere)
    full = np.asarray(x)
    dist = ((np.asarray(q)[:, None, :] - full[None, :, :]) ** 2).sum(-1)
    want = np.argsort(dist, axis=1)[:, :4]
    got = np.asarray(i)
    recall = np.mean([len(set(got[r]) & set(want[r])) / 4 for r in range(8)])
    assert recall > 0.99, recall

    # session registry (raft-dask Comms.init/local_handle analog,
    # raft_dask/common/comms.py:173,248,269): two concurrent sessions on
    # this worker, collectives routed through each session's handle
    from raft_tpu.comms import CommsSession, get_comm_state, session_handle

    s1 = CommsSession(mesh).init()
    s2 = CommsSession(mesh).init()
    assert s1.sessionId != s2.sessionId
    assert set(get_comm_state(None)) >= {s1.sessionId, s2.sessionId}
    for s, mult in ((s1, 1.0), (s2, 2.0)):
        h = session_handle(s.sessionId)
        assert h is not None and h.mesh is mesh

        def g(x, _c=h.comms):
            return _c.allreduce(x)

        z = jax.jit(shard_map(g, mesh=h.mesh, in_specs=P("shard"),
                              out_specs=P()))(
            jnp.full((nproc,), mult, jnp.float32)
        )
        assert float(z[0]) == nproc * mult, (s.sessionId, float(z[0]))
    s1.destroy()
    assert session_handle(s2.sessionId) is not None
    assert get_comm_state(None).get(s1.sessionId) is None
    s2.destroy()
    print(f"proc{pid} OK", flush=True)
    """
)


def _launch_once(worker, env):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out.decode())
    finally:
        for p in procs:  # never leak hung rendezvous workers
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_multihost(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    )
    env.pop("XLA_FLAGS", None)  # no virtual device splitting in workers
    # the bind-then-close port pick can race other processes: retry once
    # with a fresh port before declaring failure
    for attempt in (0, 1):
        procs, outs = _launch_once(worker, env)
        if all(p.returncode == 0 for p in procs) or attempt == 1:
            break
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{pid} failed:\n{out[-2000:]}"
        assert f"proc{pid} OK" in out
