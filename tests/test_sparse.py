"""Sparse subsystem tests — scipy.sparse / scipy.sparse.csgraph oracles.

Mirrors the reference's sparse test strategy (cpp/test/sparse/*.cu:
conversion round-trips, op correctness vs dense math, distances vs dense
engine, MST weight vs csgraph, CC vs csgraph).
"""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sps
import scipy.sparse.csgraph as csgraph

from raft_tpu import sparse
from raft_tpu.sparse import COO, CSR


def _rand_sparse(m, n, density, seed):
    rng = np.random.default_rng(seed)
    mat = sps.random(
        m, n, density=density, random_state=rng, format="csr",
        data_rvs=lambda k: rng.uniform(0.1, 1.0, k),
    )
    return mat


class TestTypesConvert:
    def test_roundtrip_dense(self):
        x = _rand_sparse(37, 53, 0.15, 0).toarray().astype(np.float32)
        coo = sparse.dense_to_coo(x)
        np.testing.assert_allclose(np.asarray(coo.to_dense()), x)
        csr = sparse.coo_to_csr(coo)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), x)
        back = sparse.csr_to_coo(csr)
        np.testing.assert_allclose(np.asarray(back.to_dense()), x)

    def test_scipy_interop(self):
        m = _rand_sparse(20, 30, 0.2, 1)
        csr = sparse.from_scipy(m)
        assert csr.nnz == m.nnz
        back = sparse.to_scipy(csr)
        np.testing.assert_allclose(back.toarray(), m.toarray(), rtol=1e-6)

    def test_coo_sort(self):
        rng = np.random.default_rng(2)
        rows = rng.integers(0, 10, 50).astype(np.int32)
        cols = rng.integers(0, 10, 50).astype(np.int32)
        vals = rng.uniform(size=50).astype(np.float32)
        s = sparse.coo_sort(COO(rows, cols, vals, (10, 10)))
        r, c = np.asarray(s.rows), np.asarray(s.cols)
        key = r.astype(np.int64) * 10 + c
        assert (np.diff(key) >= 0).all()


class TestOps:
    def test_sum_duplicates(self):
        rows = np.array([0, 0, 1, 0], np.int32)
        cols = np.array([1, 1, 2, 1], np.int32)
        vals = np.array([1.0, 2.0, 5.0, 4.0], np.float32)
        out = sparse.op.sum_duplicates(COO(rows, cols, vals, (3, 3)))
        dense = np.asarray(out.to_dense())
        assert dense[0, 1] == 7.0 and dense[1, 2] == 5.0
        assert out.nnz == 2

    def test_symmetrize_max(self):
        # knn-style asymmetric graph
        coo = COO(
            np.array([0, 1], np.int32), np.array([1, 2], np.int32),
            np.array([3.0, 4.0], np.float32), (3, 3),
        )
        sym = sparse.op.symmetrize(coo, mode="max")
        d = np.asarray(sym.to_dense())
        assert d[0, 1] == d[1, 0] == 3.0
        assert d[1, 2] == d[2, 1] == 4.0

    def test_degree_and_remove_scalar(self):
        x = _rand_sparse(15, 15, 0.3, 3)
        coo = sparse.from_scipy(x)
        deg = np.asarray(sparse.op.degree(sparse.csr_to_coo(coo)))
        np.testing.assert_array_equal(deg, np.diff(x.indptr))

    def test_row_slice(self):
        x = _rand_sparse(20, 10, 0.3, 4)
        csr = sparse.from_scipy(x)
        sl = sparse.op.row_slice(csr, 5, 12)
        np.testing.assert_allclose(
            np.asarray(sl.to_dense()), x[5:12].toarray(), rtol=1e-6
        )


class TestLinalg:
    def test_spmv_spmm(self):
        x = _rand_sparse(40, 30, 0.2, 5)
        csr = sparse.from_scipy(x)
        v = np.random.default_rng(6).standard_normal(30).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sparse.linalg.spmv(csr, v)), x @ v, rtol=1e-4,
            atol=1e-5,
        )
        b = np.random.default_rng(7).standard_normal((30, 8)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sparse.linalg.spmm(csr, b)), x @ b, rtol=1e-5,
            atol=1e-6,
        )

    def test_transpose_add_norm(self):
        x = _rand_sparse(25, 18, 0.25, 8)
        csr = sparse.from_scipy(x)
        t = sparse.linalg.transpose(csr)
        np.testing.assert_allclose(
            np.asarray(t.to_dense()), x.T.toarray(), rtol=1e-6
        )
        y = _rand_sparse(25, 18, 0.25, 9)
        s = sparse.linalg.add(csr, sparse.from_scipy(y))
        np.testing.assert_allclose(
            np.asarray(s.to_dense()), (x + y).toarray(), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(sparse.linalg.row_norm(csr, "l1")),
            np.abs(x).sum(1).A1 if hasattr(np.abs(x).sum(1), "A1")
            else np.asarray(np.abs(x).sum(1)).ravel(),
            rtol=1e-6,
        )

    def test_laplacian(self):
        x = _rand_sparse(12, 12, 0.3, 10)
        adj = (x + x.T) * 0.5
        adj.setdiag(0)
        adj.eliminate_zeros()
        csr = sparse.from_scipy(adj)
        lap, d = sparse.linalg.laplacian(csr)
        want = csgraph.laplacian(adj.tocsr())
        np.testing.assert_allclose(
            np.asarray(lap.to_dense()), want.toarray(), rtol=1e-5, atol=1e-6
        )


class TestSparseDistance:
    @pytest.mark.parametrize(
        "metric",
        ["sqeuclidean", "euclidean", "cosine", "l1", "linf", "canberra",
         "inner_product", "braycurtis", "hamming"],
    )
    def test_vs_dense_engine(self, metric):
        from raft_tpu.distance.pairwise import pairwise_distance as dense_pd

        xs = _rand_sparse(33, 47, 0.3, 11)
        ys = _rand_sparse(21, 47, 0.3, 12)
        got = np.asarray(
            sparse.distance.pairwise_distance(
                sparse.from_scipy(xs), sparse.from_scipy(ys), metric,
                block_rows=16,
            )
        )
        want = np.asarray(
            dense_pd(xs.toarray().astype(np.float32),
                     ys.toarray().astype(np.float32), metric)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_knn_vs_dense(self):
        xs = _rand_sparse(50, 31, 0.4, 13)
        ys = _rand_sparse(80, 31, 0.4, 14)
        d, i = sparse.neighbors.brute_force_knn(
            sparse.from_scipy(xs), sparse.from_scipy(ys), k=5,
            metric="sqeuclidean",
        )
        from sklearn.neighbors import NearestNeighbors

        nn = NearestNeighbors(n_neighbors=5, metric="sqeuclidean").fit(
            ys.toarray()
        )
        wd, wi = nn.kneighbors(xs.toarray())
        np.testing.assert_allclose(np.sort(np.asarray(d), 1), np.sort(wd, 1),
                                   rtol=1e-4, atol=1e-4)


class TestMST:
    def test_mst_weight_vs_csgraph(self):
        rng = np.random.default_rng(15)
        n = 60
        x = rng.standard_normal((n, 3)).astype(np.float32)
        # dense complete graph on pairwise distances
        from scipy.spatial.distance import squareform, pdist

        d = squareform(pdist(x)).astype(np.float32)
        iu = np.triu_indices(n, 1)
        coo = COO(
            iu[0].astype(np.int32), iu[1].astype(np.int32),
            d[iu].astype(np.float32), (n, n),
        )
        src, dst, w, colors = sparse.mst(coo)
        want = csgraph.minimum_spanning_tree(sps.csr_matrix(np.triu(d)))
        assert src.shape[0] == n - 1
        np.testing.assert_allclose(w.sum(), want.sum(), rtol=1e-5)

    def test_mst_forest_disconnected(self):
        # two disjoint triangles -> 4 edges, 2 components
        rows = np.array([0, 1, 2, 3, 4, 5], np.int32)
        cols = np.array([1, 2, 0, 4, 5, 3], np.int32)
        vals = np.array([1.0, 2.0, 3.0, 1.0, 2.0, 3.0], np.float32)
        src, dst, w, colors = sparse.mst(COO(rows, cols, vals, (6, 6)))
        assert src.shape[0] == 4
        assert w.sum() == 6.0
        ncc, labels = sparse.connected_components(COO(rows, cols, vals, (6, 6)))
        assert ncc == 2
        labels = np.asarray(labels)
        assert labels[0] == labels[1] == labels[2]
        assert labels[3] == labels[4] == labels[5]
        assert labels[0] != labels[3]

    def test_mst_ties(self):
        # all-equal weights: any spanning tree is minimal; must not hang
        n = 16
        rng = np.random.default_rng(16)
        rows, cols = np.meshgrid(np.arange(n), np.arange(n))
        mask = rows < cols
        coo = COO(
            rows[mask].astype(np.int32), cols[mask].astype(np.int32),
            np.ones(int(mask.sum()), np.float32), (n, n),
        )
        src, dst, w, colors = sparse.mst(coo)
        assert src.shape[0] == n - 1
        assert w.sum() == n - 1

    def test_connect_components(self):
        rng = np.random.default_rng(17)
        a = rng.standard_normal((10, 4)).astype(np.float32)
        b = rng.standard_normal((10, 4)).astype(np.float32) + 50.0
        x = np.vstack([a, b])
        colors = np.array([0] * 10 + [1] * 10, np.int32)
        src, dst, w = sparse.solver.connect_components(x, colors)
        assert len(src) >= 1
        # every bridging edge crosses the partition
        for s, t in zip(src, dst):
            assert colors[s] != colors[t]


class TestKnnGraph:
    def test_knn_graph_degree(self):
        rng = np.random.default_rng(18)
        x = rng.standard_normal((40, 8)).astype(np.float32)
        g = sparse.neighbors.knn_graph(x, k=5)
        assert g.nnz == 40 * 5
        rows = np.asarray(g.rows)
        np.testing.assert_array_equal(np.bincount(rows, minlength=40),
                                      np.full(40, 5))
        # no self edges
        assert (np.asarray(g.rows) != np.asarray(g.cols)).all()


def test_pairwise_colblocked_high_dim(rng):
    """Vocab-sized feature dim: the column-blocked engine matches scipy
    on expanded, additive and max-combine metrics (the reference handles
    this regime via COO-SpMV strategies, detail/coo_spmv.cuh)."""
    import scipy.sparse as sp
    from scipy.spatial.distance import cdist
    from raft_tpu.sparse import distance as sd
    from raft_tpu.sparse.types import CSR

    m, n, D = 40, 30, 50_000
    xs = sp.random(m, D, density=0.002, random_state=1, format="csr",
                   dtype=np.float32)
    ys = sp.random(n, D, density=0.002, random_state=2, format="csr",
                   dtype=np.float32)
    x = CSR(jnp.asarray(xs.indptr), jnp.asarray(xs.indices),
            jnp.asarray(xs.data), (m, D))
    y = CSR(jnp.asarray(ys.indptr), jnp.asarray(ys.indices),
            jnp.asarray(ys.data), (n, D))
    xd, yd = xs.toarray(), ys.toarray()
    for metric, want in [
        ("sqeuclidean", cdist(xd, yd, "sqeuclidean")),
        ("inner_product", xd @ yd.T),   # library convention: raw dot
        ("cosine", cdist(xd, yd, "cosine")),
        ("l1", cdist(xd, yd, "cityblock")),
        ("linf", cdist(xd, yd, "chebyshev")),
    ]:
        got = np.asarray(sd.pairwise_distance(x, y, metric=metric,
                                              col_block=4096))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
