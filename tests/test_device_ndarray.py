"""device_ndarray / DLPack interop tests (pylibraft
common/device_ndarray.py parity; torch interop via DLPack)."""

import numpy as np
import pytest

from raft_tpu.core.device_ndarray import (
    auto_convert_output,
    cai_wrapper,
    device_ndarray,
)


def test_roundtrip_host():
    x = np.random.default_rng(0).standard_normal((10, 4)).astype(np.float32)
    d = device_ndarray(x)
    assert d.shape == (10, 4)
    assert d.dtype == np.float32
    assert d.c_contiguous
    np.testing.assert_array_equal(d.copy_to_host(), x)
    np.testing.assert_array_equal(np.asarray(d), x)


def test_empty_and_strides():
    d = device_ndarray.empty((3, 5), np.int32)
    assert d.shape == (3, 5) and d.dtype == np.int32
    assert d.strides == (20, 4)


def test_dlpack_numpy():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    d = device_ndarray(x)
    back = np.from_dlpack(d)
    np.testing.assert_array_equal(back, x)


def test_dlpack_torch():
    torch = pytest.importorskip("torch")
    t = torch.arange(8, dtype=torch.float32).reshape(2, 4)
    d = device_ndarray(t)
    assert d.shape == (2, 4)
    np.testing.assert_array_equal(d.copy_to_host(), t.numpy())
    back = torch.from_dlpack(d)
    assert back.shape == (2, 4)


def test_auto_convert_output_and_cai():
    import jax.numpy as jnp

    @auto_convert_output
    def f():
        return jnp.ones((2, 2)), [jnp.zeros(3), "meta"]

    a, (b, meta) = f()
    assert isinstance(a, device_ndarray)
    assert isinstance(b, device_ndarray)
    assert meta == "meta"
    arr = cai_wrapper(a)
    assert arr.shape == (2, 2)


def test_search_pipeline_through_wrapper():
    import jax.numpy as jnp
    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(1)
    x = device_ndarray(rng.standard_normal((500, 8)).astype(np.float32))
    q = device_ndarray(rng.standard_normal((20, 8)).astype(np.float32))
    d, i = brute_force.knn(cai_wrapper(q), cai_wrapper(x), 5)
    assert i.shape == (20, 5)


def test_output_format_hook():
    """config.set_output_as converts outputs globally (pylibraft
    config.set_output_as analog)."""
    import jax
    import numpy as np

    import raft_tpu.config as config
    from raft_tpu.core.device_ndarray import device_ndarray

    arr = device_ndarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    try:
        assert isinstance(arr.get(), jax.Array)
        config.set_output_as("numpy")
        out = arr.get()
        assert isinstance(out, np.ndarray)
        np.testing.assert_array_equal(out, np.arange(6).reshape(2, 3))
        config.set_output_as(lambda x: ("wrapped", x))
        assert arr.get()[0] == "wrapped"
        try:
            config.set_output_as("cupy")
            raise AssertionError("expected ValueError")
        except ValueError:
            pass
    finally:
        config.set_output_as("jax")
