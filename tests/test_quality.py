"""graft-gauge tests (ISSUE 19, marker ``serve``; docs/serving.md §14).

Covers the online-recall estimator end to end: the Wilson interval
math, the batcher's bounded best-effort shadow lane (drop-oldest, no
live backpressure), the oracle-rung selection that keeps a crippled
swap from scoring itself perfect, the :class:`QualityMonitor` closed
loop — estimates, bounded tighten/relax retunes with hysteresis, swap
probation with expiry and rollback — driven through a stub serving
unit, the fleet-level quality view (``Fabric.recall_estimates`` /
helm quality alarms), and the live server integration: the off-path
contracts (rate=0 → no monitor; obs off → the shadow lane stays dark
and retains nothing), shadow sampling through a real server with
zero steady-state retraces, and the ``slow``-marked swap-probation
rollback acceptance."""

import os
import threading
import time
import tracemalloc
import types
from concurrent.futures import Future

import numpy as np
import pytest

from raft_tpu import obs, serve, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.neighbors import ivf_flat
from raft_tpu.resilience import faultinject
from raft_tpu.serve import engine as serve_engine
from raft_tpu.serve import quality
from raft_tpu.serve.adaptive import AdaptivePolicy
from raft_tpu.serve.batcher import Batch, MicroBatcher, Request
from raft_tpu.serve.controller import HelmController
from raft_tpu.serve.fabric import Fabric
from raft_tpu.serve.quality import QualityMonitor, ShadowSample, \
    wilson_interval
from raft_tpu.serve.registry import Registry

pytestmark = [pytest.mark.serve, pytest.mark.threadsan]

N, DIM = 320, 16


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_VAR, "1")
    monkeypatch.delenv("RAFT_TPU_OBS", raising=False)
    obs.set_mode(None)
    obs.reset()
    faultinject.clear()
    yield
    obs.reset()
    obs.set_mode(None)
    faultinject.clear()
    tuning.reload()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    x = rng.standard_normal((N, DIM)).astype(np.float32)
    q = rng.standard_normal((24, DIM)).astype(np.float32)
    return x, q


def _params(**kw):
    kw.setdefault("max_batch_rows", 16)
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("max_k", 8)
    return serve.ServeParams(**kw)


def _value(snap, name, /, **labels):
    want = {str(k): str(v) for k, v in labels.items()}
    for p in snap["metrics"].get(name, {}).get("points", []):
        if all(p["labels"].get(k) == v for k, v in want.items()):
            return p.get("value", p)
    return None


# ---------------------------------------------------------------------------
# wilson interval
# ---------------------------------------------------------------------------


def test_wilson_interval_math():
    # no data -> the vacuous interval, not a crash
    assert wilson_interval(0, 0) == (0.0, 1.0)
    lo, hi = wilson_interval(9, 10)
    assert 0.0 <= lo < 0.9 < hi <= 1.0
    # perfect small-n success keeps an honest lower bound under 1
    lo, hi = wilson_interval(8, 8)
    assert hi == 1.0 and lo < 1.0
    # the interval narrows as n grows at fixed p
    lo_s, hi_s = wilson_interval(8, 16)
    lo_l, hi_l = wilson_interval(128, 256)
    assert (hi_l - lo_l) < (hi_s - lo_s)
    assert lo_l < 0.5 < hi_l
    # degenerate inputs clamp instead of escaping [0, 1]
    lo, hi = wilson_interval(20, 10)
    assert 0.0 <= lo <= hi <= 1.0


# ---------------------------------------------------------------------------
# the batcher's shadow lane
# ---------------------------------------------------------------------------


def _shadow_req(rows=1):
    return Request(queries=np.zeros((rows, 4), np.float32), k=1,
                   prefilter=None, future=Future())


def test_shadow_lane_bounded_drop_oldest():
    started = threading.Event()
    release = threading.Event()

    def dispatch(b):
        if not b.shadow:
            started.set()
            release.wait(timeout=10)

    mb = MicroBatcher(dispatch, max_batch_rows=8, max_wait_ms=0.0,
                      shadow_queue_rows=4, name="q")
    try:
        # park the dispatcher in a live batch so the shadow lane can
        # actually accumulate (it only drains when the thread is idle)
        mb.submit(np.zeros((1, 4), np.float32), 1)
        assert started.wait(timeout=10)
        reqs = [_shadow_req() for _ in range(6)]
        dropped = []
        for r in reqs:
            dropped += mb.submit_shadow(r)
        # past the 4-row cap the OLDEST queued samples fall out, in
        # order, and are handed BACK (the caller owns their pins)
        assert len(dropped) == 2
        assert dropped[0] is reqs[0] and dropped[1] is reqs[1]
        # a sample alone exceeding the cap bounces immediately
        big = _shadow_req(rows=5)
        assert mb.submit_shadow(big) == [big]
        left = mb.drain_shadow()
        assert len(left) == 4
        assert all(a is b for a, b in zip(left, reqs[2:]))
        assert mb.drain_shadow() == []        # rows accounting reset
        one = _shadow_req()
        assert mb.submit_shadow(one) == []    # space again after drain
        assert mb.drain_shadow() == [one]
        # live admission never saw shadow rows: the queue accepted a
        # full live load while the shadow lane churned above
        assert mb.depth_rows() == 0
    finally:
        release.set()
        mb.close()
    # closed batcher hands every sample straight back
    post = _shadow_req()
    assert mb.submit_shadow(post) == [post]


# ---------------------------------------------------------------------------
# oracle rung selection
# ---------------------------------------------------------------------------


def _orung(algo, n_lists=16, n_probes=4):
    stub = types.SimpleNamespace(
        algo=algo,
        index=types.SimpleNamespace(n_lists=n_lists),
        search_params=types.SimpleNamespace(n_probes=n_probes))
    return serve_engine._Handle.oracle_rung(stub)


def test_oracle_rung_outranks_any_serving_ceiling():
    # the under-trained-swap trap: a generation crippled to n_probes=1
    # must NOT be its own oracle — the full probe count is the truth
    assert _orung("ivf_flat", n_lists=16, n_probes=1) == 16
    assert _orung("ivf_pq", n_lists=32, n_probes=4) == 32
    # ceiling already at the top tier: the resolved exhaustive program
    # IS the oracle, no extra trace needed
    assert _orung("ivf_flat", n_lists=16, n_probes=16) is None
    # no probe axis to escalate
    assert _orung("brute_force") is None
    assert _orung("cagra") is None


def test_rung_params_override_on_non_adaptive_ivf():
    sp = ivf_flat.SearchParams(n_probes=2)
    stub = types.SimpleNamespace(algo="ivf_flat", adaptive=None,
                                 search_params=sp,
                                 pipeline_rr=lambda: 1)
    over, rr = serve_engine._Handle.rung_params(stub, 16)
    assert over.n_probes == 16 and rr == 1
    # rung=None hands back the resolved params verbatim
    verbatim, _ = serve_engine._Handle.rung_params(stub, None)
    assert verbatim is sp


def test_exact_tier_oracle_debiases_quantized_overscore():
    """ROADMAP 9(a): a quantized oracle scores its own quantization
    error as ground truth — candidates IT mis-ranks look "matched"
    whenever serving mis-ranks them the same way, so the recall
    estimate reads high exactly where it matters.  When the generation
    carries an exact tier (``dataset=`` / a RerankSource), the oracle
    rung becomes the exact-rerank PLAN (``"exact"``): exhaustive
    probing + exact re-rank, whose answers track true recall."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force, ivf_pq

    rng = np.random.default_rng(11)
    n, dim, k = 2048, 32, 8
    x = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((48, dim)).astype(np.float32)
    _, ti = brute_force.knn(q, x, k, metric="sqeuclidean")
    truth = [set(map(int, row)) for row in np.asarray(ti)]

    bp = ivf_pq.IndexParams(n_lists=16, pq_dim=4, metric="sqeuclidean")
    sp = ivf_pq.SearchParams(n_probes=4, local_recall_target=1.0)

    def overlap(ids, oracle_sets):
        ids = np.asarray(ids)
        return float(np.mean([
            len(set(map(int, ids[r])) & oracle_sets[r]) / k
            for r in range(ids.shape[0])]))

    with serve.Server(_params(warmup=False)) as srv:
        # generation WITH the exact tier (dataset kept)
        srv.create_index("a", x, algo="ivf_pq", build_params=bp,
                         search_params=sp, refine_ratio=32, warmup=False)
        ha = srv.registry.get("a").handle
        # same index WITHOUT an exact tier: the quantizer is all it has
        srv.add_index("b", ha.index, algo="ivf_pq", search_params=sp,
                      warmup=False)
        hb = srv.registry.get("b").handle

        # rung selection: the tier flips the oracle to the exact plan
        assert ha.oracle_rung() == "exact"
        assert hb.oracle_rung() == 16

        qd = jnp.asarray(q)
        _, served = hb.compiled(k, None)(qd)
        _, quant_oracle = hb.compiled(k, hb.oracle_rung())(qd)
        _, exact_oracle = ha.compiled(k, ha.oracle_rung())(qd)

        exact_sets = [set(map(int, row)) for row in np.asarray(exact_oracle)]
        quant_sets = [set(map(int, row)) for row in np.asarray(quant_oracle)]

        # the exact-tier oracle IS (near) ground truth; the quantized
        # oracle is not even close on a pq_dim=4 quantizer
        assert overlap(np.asarray(exact_oracle), truth) > 0.95
        assert overlap(np.asarray(quant_oracle), truth) < 0.8

        true_recall = overlap(served, truth)
        quant_scored = overlap(served, quant_sets)
        exact_scored = overlap(served, exact_sets)
        # quantized oracle OVER-scores the served answers...
        assert quant_scored > true_recall + 0.1
        # ...the exact-tier oracle does not (tracks true recall)
        assert abs(exact_scored - true_recall) < 0.05


# ---------------------------------------------------------------------------
# QualityMonitor closed loop (stub serving unit)
# ---------------------------------------------------------------------------


def _stub_serving(registry=None, warmup_enabled=False, **pkw):
    pkw.setdefault("quality_sample_rate", 1.0)
    pkw.setdefault("quality_band", 0.9)
    pkw.setdefault("quality_window", 8)
    pkw.setdefault("quality_min_samples", 4)
    warmed = []
    s = types.SimpleNamespace(
        params=serve.ServeParams(**pkw),
        registry=registry if registry is not None else Registry(),
        batcher=None,
        warmup_enabled=warmup_enabled,
        warmup_handle=warmed.append)
    s.warmed = warmed
    return s


def _feed(mon, gen, recalls, k=4, rung=None):
    """Score one synthetic shadow batch: sample i matches
    ``round(recalls[i] * k)`` of the oracle's k slots."""
    reqs, truth_rows = [], []
    for rc in recalls:
        m = int(round(rc * k))
        served = np.arange(k, dtype=np.int64)[None, :]
        truth = np.concatenate([np.arange(m),
                                np.arange(100, 100 + k - m)])
        gen.pin()
        reqs.append(Request(
            queries=np.zeros((1, DIM), np.float32), k=k,
            prefilter=None, future=Future(),
            shadow=ShadowSample(gen, rung, served, k)))
        truth_rows.append(truth.astype(np.int64))
    batch = Batch(requests=reqs, rows=len(reqs), bucket=len(reqs),
                  prefilter=None, rung=rung, shadow=True)
    try:
        mon.score_batch(batch, np.stack(truth_rows))
    finally:
        for r in reqs:
            r.shadow.gen.release()


def test_monitor_estimates_per_rung_and_masks_invalid_slots():
    serving = _stub_serving()
    gen = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon = QualityMonitor(serving, "t")
    _feed(mon, gen, [1.0] * 4, rung=2)
    _feed(mon, gen, [0.5] * 4, rung=8)
    st = mon.stats()
    assert st["samples"] == 8 and st["band"] == 0.9
    assert st["estimate"] == 0.75          # pooled 24/32
    assert st["ci_low"] < 0.75 < st["ci_high"] < 0.9
    assert st["slots"] == 32
    # masked -1 slots count for neither side: truth has 2 live slots,
    # served matches one of them -> 1/2, not 1/4
    gen2 = serving.registry.publish(
        "m", types.SimpleNamespace(adaptive=None))
    mon2 = QualityMonitor(serving, "m")
    gen2.pin()
    req = Request(queries=np.zeros((1, DIM), np.float32), k=4,
                  prefilter=None, future=Future(),
                  shadow=ShadowSample(
                      gen2, None,
                      np.array([[5, 7, -1, -1]], np.int64), 4))
    batch = Batch(requests=[req], rows=1, bucket=1, prefilter=None,
                  shadow=True)
    mon2.score_batch(batch, np.array([[5, 6, -1, -1]], np.int64))
    gen2.release()
    assert mon2.stats()["estimate"] == 0.5


def test_monitor_tighten_is_bounded_and_relax_is_exact():
    serving = _stub_serving(quality_max_retunes=2)
    pol = AdaptivePolicy.build(ceiling=8, list_cap=64)
    h = types.SimpleNamespace(adaptive=pol)
    gen = serving.registry.publish("t", h)
    mon = QualityMonitor(serving, "t")
    base_easy = pol.easy_margin

    _feed(mon, gen, [0.5] * 8)
    assert mon.stats()["retune_steps"] == 1
    assert h.adaptive.easy_margin == pytest.approx(
        min(base_easy * 2, 0.95))
    # the retune reset the window: verdicts come from post-retune
    # samples only
    assert mon.stats()["samples"] == 0 and mon.stats()["estimate"] is None
    _feed(mon, gen, [0.5] * 8)
    assert mon.stats()["retune_steps"] == 2
    # bounded: quality_max_retunes caps the ratchet
    _feed(mon, gen, [0.5] * 8)
    assert mon.stats()["retune_steps"] == 2
    # recovery: ci_low must clear band + hysteresis (k=8 gives the
    # window enough slots) before one exact relax step fires
    _feed(mon, gen, [1.0] * 8, k=8)
    st = mon.stats()
    assert st["retune_steps"] == 1
    assert [a[0] for a in st["actions"]] == \
        ["tighten", "tighten", "relax"]
    # relax is base.tightened()^1, not a drifting inverse
    assert h.adaptive.easy_margin == pytest.approx(
        min(base_easy * 2, 0.95))


def test_monitor_defers_refine_rewarm_out_of_the_lock():
    # refine_ratio=2 -> tightened() doubles the over-fetch, the refine
    # ladder grows, and the re-warm must run AFTER the monitor lock is
    # released (the GL013 quality->mutation edge), via the serving unit
    serving = _stub_serving(warmup_enabled=True)
    pol = AdaptivePolicy.build(ceiling=8, list_cap=64, refine_ratio=2)
    h = types.SimpleNamespace(adaptive=pol)
    gen = serving.registry.publish("t", h)
    mon = QualityMonitor(serving, "t")
    _feed(mon, gen, [0.5] * 8)
    assert serving.warmed == [h]
    assert mon._deferred_rewarm is None
    assert h.adaptive.refine_ladder() != pol.refine_ladder()


def test_monitor_probation_rollback_restores_predecessor():
    serving = _stub_serving(quality_min_samples=4, quality_retune=False)
    handle_a = types.SimpleNamespace(adaptive=None)
    gen1 = serving.registry.publish("t", handle_a)
    mon = QualityMonitor(serving, "t")
    _feed(mon, gen1, [1.0] * 8)                # healthy baseline
    assert mon.stats()["estimate"] == 1.0

    mon.before_publish()                        # Server._publish_guarded
    gen2 = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon.after_publish(gen2)
    assert mon.stats()["probation_open"]
    assert mon.stats()["estimate"] is None      # successor starts fresh

    _feed(mon, gen2, [0.5] * 8)                 # the swap degraded
    st = mon.stats()
    assert [a[0] for a in st["actions"]] == ["rollback"]
    detail = st["actions"][0][1]
    assert detail["to_version"] == 1 and detail["prev_estimate"] == 1.0
    cur = serving.registry.get("t")
    assert cur.version == 3 and cur.handle is handle_a
    assert not st["probation_open"]
    # fresh verdicts for the restored generation
    assert st["samples"] == 0 and st["estimate"] is None


def test_monitor_probation_expires_and_releases_the_pin():
    serving = _stub_serving()
    gen1 = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon = QualityMonitor(serving, "t")
    _feed(mon, gen1, [1.0] * 8)
    mon.before_publish()
    gen2 = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon.after_publish(gen2)
    assert not gen1.drained.is_set()    # probation pin holds it alive
    # the successor holds the band for a full window of its own samples
    _feed(mon, gen2, [1.0] * 8)
    st = mon.stats()
    assert not st["probation_open"] and not st["actions"]
    assert serving.registry.get("t").version == 2
    # probation's was the last pin: expiry lets the predecessor drain
    assert gen1.drained.is_set()


def test_monitor_rollback_disabled_leaves_the_swap():
    serving = _stub_serving(quality_rollback=False,
                            quality_retune=False)
    gen1 = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon = QualityMonitor(serving, "t")
    _feed(mon, gen1, [1.0] * 8)
    mon.before_publish()
    gen2 = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon.after_publish(gen2)
    _feed(mon, gen2, [0.5] * 8)
    assert not mon.stats()["actions"]
    assert serving.registry.get("t").version == 2


def test_offer_strides_copies_and_pins(data):
    x, q = data
    obs.set_mode("on")
    collected = []
    serving = _stub_serving(quality_sample_rate=0.5)
    serving.batcher = types.SimpleNamespace(
        submit_shadow=lambda r: collected.append(r) or [])
    gen = serving.registry.publish(
        "t", types.SimpleNamespace(adaptive=None))
    mon = QualityMonitor(serving, "t")
    assert mon.stride == 2
    reqs = [Request(queries=q[j:j + 1], k=3, prefilter=None,
                    future=Future()) for j in range(4)]
    batch = Batch(requests=reqs, rows=4, bucket=4, prefilter=None,
                  rung=2)
    ext = np.arange(4 * 3, dtype=np.int64).reshape(4, 3)
    h = types.SimpleNamespace(dtype=np.float32)
    mon.offer(batch, gen, h, ext)
    # stride 2 over 4 requests: the 2nd and 4th are sampled, each
    # carrying a COPY of its served ids and its own generation pin
    assert len(collected) == 2 and gen.refs == 2
    s = collected[0].shadow
    assert isinstance(s, ShadowSample) and s.rung == 2 and s.k == 3
    np.testing.assert_array_equal(s.served, ext[1:2, :3])
    assert s.served.base is None              # a copy, not a view
    for r in collected:
        r.shadow.gen.release()

    # overflow hand-back: the monitor releases the dropped pins
    serving.batcher.submit_shadow = lambda r: [r]
    mon.offer(batch, gen, h, ext)
    assert gen.refs == 0

    # obs off: the delivery hook is one module-attribute read — the
    # tick never advances, nothing is queued
    obs.set_mode("off")
    collected.clear()
    tick = mon._tick
    mon.offer(batch, gen, h, ext)
    assert not collected and mon._tick == tick


# ---------------------------------------------------------------------------
# fleet view: Fabric.recall_estimates + helm quality alarms
# ---------------------------------------------------------------------------


def test_fabric_recall_estimates_regroups_federated_series():
    fed = {"metrics": {
        "serve.recall_estimate": {"points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.95},
            {"labels": {"index": "t", "rung": "8"}, "value": 0.9}]},
        "serve.recall_ci_low": {"points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.91}]},
        "serve.recall_ci_high": {"points": [
            {"labels": {"worker": "w0", "index": "t", "rung": "all"},
             "value": 0.99}]},
    }}
    stub = types.SimpleNamespace(collect_metrics=lambda: fed)
    out = Fabric.recall_estimates(stub)
    assert out["w0|t|all"] == {"estimate": 0.95, "ci_low": 0.91,
                               "ci_high": 0.99}
    # a router-side series (no worker label) files under "router"
    assert out["router|t|8"] == {"estimate": 0.9}


def test_helm_quality_alarms_flag_pooled_proven_breaches_only():
    ests = {
        "w0|t|all": {"estimate": 0.6, "ci_high": 0.7},   # proven breach
        "w0|t|8": {"estimate": 0.1, "ci_high": 0.2},     # per-rung: skip
        "w1|t|all": {"estimate": 0.95, "ci_high": 0.99},
        "w2|t|all": {"estimate": 0.5},                   # no CI yet
    }
    stub = types.SimpleNamespace(
        fabric=types.SimpleNamespace(recall_estimates=lambda: ests),
        _recall_band=0.9)
    assert HelmController._quality_alarms(stub) == \
        [("quality_alarm", "w0|t|all")]
    # a mute fleet scrape degrades the alarm, never the tick
    def boom():
        raise RuntimeError("scrape down")
    stub.fabric.recall_estimates = boom
    assert HelmController._quality_alarms(stub) == []


# ---------------------------------------------------------------------------
# live server integration
# ---------------------------------------------------------------------------


def test_quality_disabled_is_one_attribute_read(data):
    x, q = data
    with serve.Server(_params(warmup=False)) as srv:
        srv.create_index("default", x)
        assert srv._servings["default"].quality is None
        srv.search(q[:4], 4)
        assert srv.stats()["quality"] is None


def test_obs_off_keeps_the_shadow_lane_dark(data):
    x, q = data
    params = _params(warmup=False, quality_sample_rate=1.0)
    with serve.Server(params) as srv:
        srv.create_index("default", x)
        mon = srv._servings["default"].quality
        assert mon is not None and not obs.enabled()
        srv.search(q[:4], 4)          # warm every lazy path first
        qfile = os.path.abspath(quality.__file__)
        tracemalloc.start()
        try:
            base = tracemalloc.take_snapshot()
            for _ in range(20):
                srv.search(q[:4], 4)
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        retained = sum(
            st.size_diff
            for st in after.compare_to(base, "filename")
            if st.traceback and st.traceback[0].filename == qfile)
        # the ENABLED gate is the whole story: no samples, no copies,
        # no pins — nothing attributable to quality.py survives
        assert retained < 256
        assert mon._tick == 0
        assert not srv._servings["default"].batcher._qs
        assert mon.stats()["samples"] == 0


def _wait_samples(mon, n, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if mon.stats()["samples"] >= n:
            return mon.stats()
        time.sleep(0.05)
    raise AssertionError(
        f"shadow lane never scored {n} samples: {mon.stats()}")


def test_shadow_sampling_live_zero_retraces(data):
    x, q = data
    obs.set_mode("on")
    params = _params(quality_sample_rate=1.0, quality_window=8,
                     quality_min_samples=4, max_wait_ms=0.5,
                     max_batch_rows=8, max_k=4)
    with serve.Server(params) as srv:
        srv.create_index("default", x)          # brute_force, warmed
        mon = srv._servings["default"].quality
        for j in range(6):
            srv.search(q[j], 4)
        st = _wait_samples(mon, 4)
        # brute force IS its own oracle: served == truth, recall 1.0
        assert st["estimate"] == 1.0 and st["ci_high"] == 1.0
        assert 0.0 < st["ci_low"] < 1.0
        assert srv.stats()["quality"]["estimate"] == 1.0

        before = serve.trace_cache_sizes()
        scored = st["samples"]
        for j in range(6):
            srv.search(q[6 + j], 4)
        _wait_samples(mon, scored + 4)
        # the oracle re-runs ride warmed (bucket, k) programs only
        assert serve.trace_cache_sizes() == before

        snap = obs.snapshot()
        assert _value(snap, "serve.recall_estimate",
                      index="default", rung="all") == 1.0
        assert _value(snap, "serve.recall_estimate",
                      index="default", rung="exhaustive") == 1.0
        assert _value(snap, "serve.recall_ci_high",
                      index="default", rung="all") == 1.0
        assert _value(snap, "serve.shadow_samples_total",
                      index="default") >= 8
        assert _value(snap, "serve.shadow_batches_total",
                      index="default") >= 1
        # the recall histogram shares the unit-interval preset
        hist = _value(snap, "serve.recall_sample",
                      index="default", rung="exhaustive")
        assert hist["buckets"] == list(obs.UNIT_BUCKETS)
        assert hist["count"] >= 8


@pytest.mark.slow
def test_swap_probation_rollback_e2e():
    """The ISSUE 19 acceptance drill: a hot-swap crippled to
    ``n_probes=1`` degrades pooled recall beyond statistical doubt on
    hard between-cluster queries; the probation window convicts the
    SWAP (the predecessor's baseline was measurably better), rolls it
    back, and the restored generation recovers — with zero new traces
    minted along the way."""
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((16, DIM)).astype(np.float32) * 5
    x = np.concatenate([
        c + rng.standard_normal((64, DIM)).astype(np.float32)
        for c in centers], axis=0)
    hard = ((centers[rng.integers(0, 16, (256,))]
             + centers[rng.integers(0, 16, (256,))]) / 2
            + 0.5 * rng.standard_normal((256, DIM))).astype(np.float32)
    obs.set_mode("on")
    params = serve.ServeParams(
        max_batch_rows=16, max_wait_ms=0.2, max_k=16,
        quality_sample_rate=1.0, quality_min_samples=8,
        quality_window=16, quality_band=0.9, quality_retune=False,
        adaptive_probes=True)
    with serve.Server(params) as srv:
        srv.create_index("t", x, algo="ivf_flat",
                         build_params=ivf_flat.IndexParams(n_lists=16))
        mon = srv._servings["t"].quality

        def traffic(n):
            for _ in range(n):
                srv.submit(hard[rng.integers(0, 256, (4,))], k=8,
                           index="t").result(timeout=60)
                time.sleep(0.005)

        traffic(24)
        _wait_samples(mon, 8)
        assert srv.generation("t") == 1

        # the crippled successor: one probe cannot cover between-
        # cluster queries, so its own exhaustive oracle convicts it
        srv.swap("t", dataset=x,
                 search_params=ivf_flat.SearchParams(n_probes=1),
                 wait=True)
        assert srv.generation("t") == 2
        n_before = serve.total_trace_count()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            traffic(8)
            acts = [a[0] for a in mon.stats()["actions"]]
            if "rollback" in acts:
                break
        st = srv.stats("t")["quality"]
        kinds = [a[0] for a in st["actions"]]
        assert "rollback" in kinds, st
        detail = dict(st["actions"][kinds.index("rollback")][1])
        assert detail["prev_estimate"] is not None
        assert detail["ci_high"] < detail["prev_estimate"] \
            - quality.ROLLBACK_MARGIN
        # the rollback is a fresh generation wrapping the healthy
        # handle — versions stay monotone
        assert srv.generation("t") >= 3
        assert not st["probation_open"]
        # the restored generation recovers inside the band
        traffic(24)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            est = srv.stats("t")["quality"]["estimate"]
            if est is not None and est >= 0.9:
                break
            traffic(8)
        assert srv.stats("t")["quality"]["estimate"] >= 0.9
        # the whole episode — crippled serving, oracle re-runs,
        # rollback, recovery — rode already-warmed programs
        assert serve.total_trace_count() == n_before
