"""LAP + label utility tests — scipy.optimize.linear_sum_assignment oracle
(mirrors cpp/test/linear_assignment.cu and cpp/test/label/*.cu)."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu import label as label_utils
from raft_tpu import solver


class TestLAP:
    @pytest.mark.parametrize("n", [4, 16, 64, 128])
    def test_optimal_cost_random(self, n):
        rng = np.random.default_rng(n)
        cost = rng.uniform(0, 10, (n, n)).astype(np.float32)
        assign, total = solver.solve(cost)
        a = np.asarray(assign)
        # valid permutation
        assert sorted(a.tolist()) == list(range(n))
        ri, ci = linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        got = cost[np.arange(n), a].sum()
        assert got <= opt * (1 + 1e-3) + 1e-2

    def test_integer_costs_exact(self):
        rng = np.random.default_rng(7)
        n = 32
        cost = rng.integers(0, 50, (n, n)).astype(np.float32)
        assign, total = solver.solve(cost)
        ri, ci = linear_sum_assignment(cost)
        assert float(total) == cost[ri, ci].sum()

    def test_maximize(self):
        rng = np.random.default_rng(8)
        n = 16
        cost = rng.integers(0, 30, (n, n)).astype(np.float32)
        assign, total = solver.solve(cost, maximize=True)
        ri, ci = linear_sum_assignment(cost, maximize=True)
        assert float(total) == cost[ri, ci].sum()

    def test_object_api(self):
        rng = np.random.default_rng(9)
        n = 10
        cost = rng.uniform(0, 5, (2, n, n)).astype(np.float32)
        lap = solver.LinearAssignmentProblem(n, batchsize=2)
        lap.solve(cost)
        for b in range(2):
            row = np.asarray(lap.getRowAssignmentVector(b))
            col = np.asarray(lap.getColAssignmentVector(b))
            assert sorted(row.tolist()) == list(range(n))
            np.testing.assert_array_equal(col[row], np.arange(n))


class TestLabel:
    def test_make_monotonic(self):
        labels = np.array([10, 3, 3, 99, 10, -5])
        mapped, uniq = label_utils.make_monotonic(labels)
        np.testing.assert_array_equal(np.asarray(uniq), [-5, 3, 10, 99])
        np.testing.assert_array_equal(np.asarray(mapped), [2, 1, 1, 3, 2, 0])

    def test_ovr(self):
        labels = np.array([0, 1, 2, 1])
        ovr = label_utils.get_ovr_labels(labels, 1)
        np.testing.assert_array_equal(np.asarray(ovr), [0, 1, 0, 1])

    def test_merge_labels_chain(self):
        # A: {0,1} {2,3}; B: {1,2} — mask on all => one merged group + {4}
        la = np.array([0, 0, 1, 1, 2])
        lb = np.array([0, 1, 1, 2, 3])
        mask = np.ones(5, bool)
        out = np.asarray(label_utils.merge_labels(la, lb, mask))
        assert out[0] == out[1] == out[2] == out[3]
        assert out[4] != out[0]

    def test_merge_labels_mask_blocks(self):
        # same as above but vertex 1 and 2 masked out of B: no bridge
        la = np.array([0, 0, 1, 1, 2])
        lb = np.array([0, 1, 1, 2, 3])
        mask = np.array([True, False, False, True, True])
        out = np.asarray(label_utils.merge_labels(la, lb, mask))
        assert out[0] == out[1]
        assert out[2] == out[3]
        assert out[0] != out[2]
